"""graftlint rules G001-G008 — each encodes one invariant this repo's
performance tricks depend on (tools/lint/README.md documents the "why"
per rule; keep that file in sync when touching these).

Conventions shared by all rules:

- a rule yields Findings; the engine drops the waived ones (see
  engine.FileContext.is_waived for the waiver grammar);
- "terminal name" matching (``lax.psum`` and ``psum`` both match
  "psum") — this codebase imports both ways, and a linter that misses
  the aliased spelling teaches people to alias around it;
- name resolution is intentionally shallow (module-level constants,
  package-wide constants): anything deeper is a heuristic, and a lint
  heuristic that guesses wrong silently is worse than one that asks for
  a waiver.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Sequence, Set, Tuple

from tools.lint.engine import (
    FileContext,
    Finding,
    PackageContext,
    dotted_name,
    is_test_path,
    resolve_int,
    resolve_str,
    terminal_name,
)

_JIT_NAMES = {"jit", "pjit"}
_SHARD_NAMES = {"shard_map", "smap", "pmap"}
_NUMPY_ROOTS = {"np", "numpy"}


class Rule:
    id: str = "G000"
    name: str = ""
    aliases: Tuple[str, ...] = ()

    def check(
        self, ctx: FileContext, pkg: PackageContext
    ) -> Iterator[Finding]:
        raise NotImplementedError

    def check_package(self, pkg: PackageContext) -> Iterator[Finding]:
        """Package-wide pass (v2 census rules); runs once after the
        per-file checks.  Findings are still waivable through the
        owning file's context."""
        return iter(())

    # Statements-with-bodies span their whole suite; binding waivers
    # across a function body would be far looser than the "inside a
    # multi-line call" grammar the tests pin, so those anchor to their
    # header line only.
    _NO_SPAN = (
        ast.FunctionDef,
        ast.AsyncFunctionDef,
        ast.ClassDef,
        ast.For,
        ast.While,
        ast.If,
        ast.Try,
        ast.With,
        ast.ExceptHandler,
    )

    def finding(
        self, ctx: FileContext, node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        end = getattr(node, "end_lineno", None) or line
        if isinstance(node, self._NO_SPAN):
            end = line
        return Finding(
            rule=self.id,
            path=ctx.path,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            snippet=ctx._line(line),
            end_line=end,
        )


def _is_jit_spelling(node: ast.AST) -> bool:
    """jit / jax.jit / pjit — as a bare reference (decorator or callee)."""
    t = terminal_name(node)
    return t in _JIT_NAMES


def _decorator_marks_device_fn(dec: ast.AST) -> bool:
    """True for @jit, @jax.jit, @shard_map, @partial(jax.jit, ...),
    @jax.jit(...)-style decorators."""
    t = terminal_name(dec)
    if t in _JIT_NAMES or t in _SHARD_NAMES:
        return True
    if isinstance(dec, ast.Call):
        ft = terminal_name(dec.func)
        if ft in _JIT_NAMES or ft in _SHARD_NAMES:
            return True
        if ft == "partial":
            for a in list(dec.args) + [kw.value for kw in dec.keywords]:
                at = terminal_name(a)
                if at in _JIT_NAMES or at in _SHARD_NAMES:
                    return True
    return False


def _device_functions(ctx: FileContext) -> List[ast.FunctionDef]:
    """Functions whose bodies are traced/compiled: @jit/@shard_map
    decorated, or ``*_kernel``-named (the Pallas kernel convention)."""
    out = []
    for node in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
        if node.name.endswith("_kernel") or node.name == "_kernel":
            out.append(node)
        elif any(_decorator_marks_device_fn(d) for d in node.decorator_list):
            out.append(node)
    return out


class HostSyncRule(Rule):
    """G001 — device→host synchronization.

    (a) Inside traced code (@jit/@shard_map/`*_kernel`), any host-sync
        call is a bug: it either fails at trace time or silently turns a
        compiled region into a round trip per dispatch.
    (b) In the device-mesh layer (``parallel/``), every ``np.asarray`` /
        ``jax.device_get`` / ``.item()`` / ``.block_until_ready()`` IS a
        device fetch crossing a link measured as low as 5 MB/s — each
        site must carry a ``# lint: fetch-site`` waiver naming why the
        fetch is necessary, so the audited-fetch-sites inventory lives
        in the code itself.
    """

    id = "G001"
    name = "host-sync"
    # fetch-site: audited device→host fetch.  host-data: the argument is
    # host-side data (e.g. a Python list of Device handles), not a device
    # array — a false-positive suppression, not a fetch audit.
    aliases = ("fetch-site", "host-data")

    # The reliability layer's audited fetch helpers
    # (fastapriori_tpu/reliability/retry.py): a sync call nested inside
    # their arguments IS the audited site — the helper failpoint-
    # instruments and retry-wraps it under the string label it takes —
    # so it needs no inline `# lint: fetch-site` waiver.  Recognized by
    # terminal name + a string site-label argument, so `retry.fetch`,
    # `fetch`, and `fetch_async` spellings all count while an unrelated
    # local `fetch()` without a label does not.
    _FETCH_HELPERS = {"fetch", "fetch_async"}
    # Path substrings where ALL host fetches need an audit waiver, not
    # just those inside traced functions: the mesh layer, the engine
    # layer's level loop (its np.asarray sites are the mining phase's
    # biggest link payloads — ROADMAP open item, extended from parallel/
    # in the reliability PR), the rule generator since its device
    # engine landed (ISSUE 4: mask/denominator fetches must stay on the
    # audited retry.fetch_async / gather path), and the serving tier
    # (ISSUE 10: every scan-result fetch on the request hot path must
    # ride the audited fetch.serve_match site).
    fetch_audit_dirs: Tuple[str, ...] = (
        "parallel/", "models/apriori", "rules/gen", "serve/",
    )

    _SYNC_ATTRS = {"item", "block_until_ready", "tolist", "copy_to_host_async"}

    def _sync_call_reason(self, node: ast.Call) -> Optional[str]:
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in self._SYNC_ATTRS:
                return f".{node.func.attr}() forces a device sync"
            d = dotted_name(node.func)
            if d is not None:
                root, _, rest = d.partition(".")
                if root in _NUMPY_ROOTS and rest in ("asarray", "array"):
                    # A literal container argument is host data already —
                    # no device round trip to audit.
                    if node.args and isinstance(
                        node.args[0],
                        (ast.List, ast.Tuple, ast.Dict, ast.Set, ast.Constant),
                    ):
                        return None
                    return f"{d}() on a device array copies it to host"
                if rest == "device_get" or d.endswith("device_get"):
                    return f"{d}() copies to host"
        elif isinstance(node.func, ast.Name):
            if node.func.id == "device_get":
                return "device_get() copies to host"
        return None

    def check(self, ctx, pkg):
        device_fns = _device_functions(ctx)
        traced_lines: Set[int] = set()
        for fn in device_fns:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                reason = self._sync_call_reason(node)
                if reason is None and isinstance(node.func, ast.Name):
                    # int()/float()/bool() on a non-constant inside traced
                    # code concretizes a tracer (host sync or trace error).
                    if node.func.id in ("int", "float", "bool") and (
                        len(node.args) == 1
                        and not isinstance(node.args[0], ast.Constant)
                    ):
                        reason = (
                            f"{node.func.id}() on a traced value forces "
                            "concretization"
                        )
                if reason is not None:
                    traced_lines.add(node.lineno)
                    yield self.finding(
                        ctx,
                        node,
                        f"host sync inside traced function "
                        f"`{fn.name}`: {reason}",
                    )
        if not any(d in ctx.path for d in self.fetch_audit_dirs):
            return
        audited = self._helper_audited_calls(ctx)
        for node in ctx.nodes(ast.Call):
            if node.lineno in traced_lines:
                continue  # already reported above
            if id(node) in audited:
                continue  # inside retry.fetch/fetch_async: audited there
            reason = self._sync_call_reason(node)
            if reason is not None:
                yield self.finding(
                    ctx,
                    node,
                    f"device fetch in the mesh layer ({reason}); annotate "
                    "the audited site with `# lint: fetch-site -- why` or "
                    "route it through retry.fetch/fetch_async",
                )

    _RETRY_MODULE = "fastapriori_tpu.reliability.retry"

    def _retry_helper_names(self, ctx) -> Set[str]:
        """Spellings of the audited helpers that provably resolve to the
        reliability module IN THIS FILE: bare names imported from it
        (``from ...retry import fetch_async``) plus the dotted
        ``retry.fetch`` / ``retry.fetch_async`` forms when ``retry`` is
        imported from the reliability package.  An unrelated local
        ``fetch(...)`` (a cache API, a kwarg) must NOT exempt the device
        sync nested in its arguments."""
        names: Set[str] = set()
        for node in ctx.nodes(ast.Import, ast.ImportFrom):
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module == self._RETRY_MODULE:
                    for a in node.names:
                        if a.name in self._FETCH_HELPERS:
                            names.add(a.asname or a.name)
                elif node.module == "fastapriori_tpu.reliability":
                    for a in node.names:
                        if a.name == "retry":
                            ref = a.asname or a.name
                            names.update(
                                f"{ref}.{h}" for h in self._FETCH_HELPERS
                            )
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == self._RETRY_MODULE:
                        ref = a.asname or a.name
                        names.update(
                            f"{ref}.{h}" for h in self._FETCH_HELPERS
                        )
        return names

    def _helper_audited_calls(self, ctx) -> Set[int]:
        """``id()``s of Call nodes nested inside an argument of an
        audited-fetch-helper call (``retry.fetch(lambda: np.asarray(x),
        "site")`` / ``retry.fetch_async(arr, "site")``) — helpers are
        matched by their RESOLVED reliability-module spelling
        (:meth:`_retry_helper_names`), with a string site label."""
        helper_names = self._retry_helper_names(ctx)
        if not helper_names:
            return set()
        out: Set[int] = set()
        for node in ctx.nodes(ast.Call):
            d = dotted_name(node.func)
            if d not in helper_names:
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            if not any(
                isinstance(a, ast.Constant) and isinstance(a.value, str)
                for a in args
            ):
                continue  # no site label: not the audited helper shape
            for a in args:
                for sub in ast.walk(a):
                    if isinstance(sub, ast.Call):
                        out.add(id(sub))
        return out


class CollectiveAxisRule(Rule):
    """G002 — collective axis names must tie back to a Mesh declaration.

    A psum over a misspelled axis name fails only at trace time on a
    mesh-bearing path — i.e. in the multi-chip job, not in unit tests.
    Axis arguments must be string literals (or constants resolving to
    literals) found in some ``Mesh(...)`` declaration in the linted
    package, or flow through an ``axis``-named parameter (the
    ``axis_name=None`` plumbing idiom, checked at its literal source).
    """

    id = "G002"
    name = "collective-axis"
    aliases = ("axis-ok",)

    _COLLECTIVES = {
        "psum": 1,
        "pmean": 1,
        "pmax": 1,
        "pmin": 1,
        "all_gather": 1,
        "psum_scatter": 1,
        "all_to_all": 1,
        "ppermute": 1,
        "axis_index": 0,
        "axis_size": 0,
    }

    def _axis_arg(self, node: ast.Call, pos: int) -> Optional[ast.AST]:
        for kw in node.keywords:
            if kw.arg == "axis_name":
                return kw.value
        if len(node.args) > pos:
            return node.args[pos]
        return None

    def _check_axis_expr(
        self, expr: ast.AST, ctx: FileContext, pkg: PackageContext
    ) -> Optional[str]:
        """None = fine; str = complaint."""
        if isinstance(expr, (ast.Tuple, ast.List)):
            for el in expr.elts:
                bad = self._check_axis_expr(el, ctx, pkg)
                if bad:
                    return bad
            return None
        s = resolve_str(expr, ctx, pkg)
        if s is not None:
            if pkg.declared_axes and s not in pkg.declared_axes:
                return (
                    f"axis name {s!r} does not appear in any Mesh "
                    f"declaration (declared: {sorted(pkg.declared_axes)})"
                )
            return None
        if isinstance(expr, ast.Constant) and expr.value is None:
            return None  # the `axis_name or identity` guard idiom
        t = terminal_name(expr)
        if t is not None and "axis" in t.lower():
            return None  # axis_name plumbing parameter
        return (
            "collective axis is not a string literal, a resolvable "
            "constant, or an `axis`-named parameter"
        )

    def check(self, ctx, pkg):
        for node in ctx.nodes(ast.Call):
            t = terminal_name(node.func)
            if t not in self._COLLECTIVES:
                continue
            expr = self._axis_arg(node, self._COLLECTIVES[t])
            if expr is None:
                continue
            complaint = self._check_axis_expr(expr, ctx, pkg)
            if complaint:
                yield self.finding(ctx, node, f"{t}: {complaint}")


class RecompileHazardRule(Rule):
    """G003 — recompile hazards.

    Each distinct static-argument value is a full XLA compile (seconds);
    unhashable statics are a TypeError at call time; a ``jax.jit`` call
    constructed inside a loop body builds a NEW cache entry per
    iteration and compiles every time.  The blessed pattern is the
    ``self._fns`` memo in parallel/mesh.py.
    """

    id = "G003"
    name = "recompile-hazard"
    aliases = ("compile-cache-ok",)

    def check(self, ctx, pkg):
        for node in ctx.nodes(ast.Call):
            if not _is_jit_spelling(node.func):
                continue
            for kw in node.keywords:
                if kw.arg in ("static_argnums", "static_argnames") and (
                    isinstance(kw.value, (ast.List, ast.Set, ast.Dict))
                ):
                    yield self.finding(
                        ctx,
                        node,
                        f"{kw.arg} given a mutable {type(kw.value).__name__}"
                        " literal — unhashable; use a tuple",
                    )
        # jit constructed inside a loop body (direct call or decorator on
        # a nested def) — a fresh jit wrapper per iteration defeats the
        # compile cache.  One recursive pass carrying an in-loop flag:
        # ast.walk from every enclosing loop would report the same call
        # once per nesting level and over-freeze the baseline.
        findings: List[Finding] = []

        def visit(node: ast.AST, in_loop: bool) -> None:
            if isinstance(node, ast.Call) and _is_jit_spelling(node.func):
                if in_loop:
                    findings.append(
                        self.finding(
                            ctx,
                            node,
                            "jit() constructed inside a loop body — every "
                            "iteration makes a new wrapper and recompiles; "
                            "hoist it (or memoize like DeviceContext._fns)",
                        )
                    )
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                if in_loop and any(
                    _decorator_marks_device_fn(d)
                    for d in node.decorator_list
                ):
                    findings.append(
                        self.finding(
                            ctx,
                            node,
                            f"@jit function `{node.name}` defined inside "
                            "a loop body recompiles per iteration",
                        )
                    )
                in_loop = False  # a nested def's body runs per-call
            elif isinstance(node, (ast.For, ast.While)):
                for child in node.body + node.orelse:
                    visit(child, True)
                for child in ast.iter_child_nodes(node):
                    if child not in node.body and child not in node.orelse:
                        visit(child, in_loop)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, in_loop)

        visit(ctx.tree, False)
        yield from findings


class DtypeDisciplineRule(Rule):
    """G004 — dtype discipline.

    Counting is int32-exact by contract (ROADMAP); 64-bit device dtypes
    silently downcast while ``jax_enable_x64`` is off, so a ``jnp.int64``
    outside the audited key-packing modules is at best a no-op and at
    worst a wrong-answer generator.  Conversely a function that claims
    exactness in its name/docstring must not accumulate through float32
    without stating its gate (the ``< 2^24`` mantissa bound) in a waiver.
    """

    id = "G004"
    name = "dtype-discipline"
    aliases = ("f32-gate", "key-packing")

    # Modules allowed to talk 64-bit on purpose (key packing packs rule
    # rows into uint64 lanes; order.py is the historical home).
    allowed_path_parts: Tuple[str, ...] = ("utils/order", "rules/gen")

    _WIDE = {"int64", "float64", "uint64"}

    def _is_jnp_root(self, d: Optional[str]) -> bool:
        return d is not None and (
            d.startswith("jnp.") or d.startswith("jax.numpy.")
        )

    def check(self, ctx, pkg):
        allowed = any(p in ctx.path for p in self.allowed_path_parts)
        if not allowed:
            for node in ctx.nodes(ast.Attribute, ast.Call):
                if (
                    isinstance(node, ast.Attribute)
                    and node.attr in self._WIDE
                    and self._is_jnp_root(dotted_name(node))
                ):
                    yield self.finding(
                        ctx,
                        node,
                        f"{dotted_name(node)} outside the key-packing "
                        "modules: 64-bit is silently downcast while "
                        "jax_enable_x64 is off",
                    )
                elif isinstance(node, ast.Call):
                    d = dotted_name(node.func)
                    if self._is_jnp_root(d):
                        for kw in node.keywords:
                            if (
                                kw.arg == "dtype"
                                and isinstance(kw.value, ast.Constant)
                                and kw.value.value in self._WIDE
                            ):
                                yield self.finding(
                                    ctx,
                                    node,
                                    f"dtype={kw.value.value!r} string on a "
                                    "jnp call outside the key-packing "
                                    "modules",
                                )
        # Exactness claims vs f32 accumulation.
        for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            doc = ast.get_docstring(fn) or ""
            if "exact" not in fn.name.lower() and not re.search(
                r"\bexact", doc, re.IGNORECASE
            ):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                for kw in node.keywords:
                    if kw.arg != "preferred_element_type":
                        continue
                    d = dotted_name(kw.value)
                    if d in ("jnp.float32", "jax.numpy.float32"):
                        yield self.finding(
                            ctx,
                            node,
                            f"`{fn.name}` claims exactness but accumulates "
                            "in float32 — state the mantissa gate "
                            "(counts < 2^24) in a `# lint: f32-gate` "
                            "waiver or accumulate in int32",
                        )


class PallasConstraintRule(Rule):
    """G005 — Pallas/TPU kernel constraints.

    Mosaic tiles are (8, 128)-granular: a BlockSpec whose trailing dims
    are not multiples of (8, 128) either fails to lower or pads and
    silently wastes VMEM.  And a Python ``if`` on a ref value inside a
    kernel body is a trace-time error masked until the kernel is next
    recompiled — use ``pl.when`` / ``jnp.where``.
    """

    id = "G005"
    name = "pallas-constraint"
    aliases = ("tile-ok",)

    def _imports_pallas(self, ctx: FileContext) -> bool:
        for node in ctx.nodes(ast.Import, ast.ImportFrom):
            if isinstance(node, ast.ImportFrom) and (
                ("pallas" in (node.module or ""))
                or any("pallas" in a.name for a in node.names)
            ):
                return True
            if isinstance(node, ast.Import) and any(
                "pallas" in a.name for a in node.names
            ):
                return True
        return False

    def check(self, ctx, pkg):
        if not self._imports_pallas(ctx):
            return
        for node in ctx.nodes(ast.Call):
            if terminal_name(node.func) != "BlockSpec":
                continue
            shape = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "block_shape":
                    shape = kw.value
            if not isinstance(shape, (ast.Tuple, ast.List)):
                continue
            dims = [resolve_int(e, ctx) for e in shape.elts]
            if len(dims) >= 1 and dims[-1] is not None and dims[-1] % 128:
                yield self.finding(
                    ctx,
                    node,
                    f"BlockSpec lane dim {dims[-1]} is not a multiple of "
                    "128 (Mosaic tile granularity)",
                )
            if len(dims) >= 2 and dims[-2] is not None and dims[-2] % 8:
                yield self.finding(
                    ctx,
                    node,
                    f"BlockSpec sublane dim {dims[-2]} is not a multiple "
                    "of 8 (Mosaic tile granularity)",
                )
        # Python `if` on ref values inside kernel bodies.
        for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            ref_params = {
                a.arg
                for a in list(fn.args.args) + list(fn.args.posonlyargs)
                if a.arg.endswith("_ref")
            }
            if not ref_params:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.IfExp)):
                    continue
                for sub in ast.walk(node.test):
                    if (
                        isinstance(sub, ast.Name)
                        and sub.id in ref_params
                    ):
                        yield self.finding(
                            ctx,
                            node,
                            f"Python `if` on ref `{sub.id}` in kernel "
                            f"`{fn.name}` — refs are traced; use pl.when "
                            "or jnp.where",
                        )
                        break


class SilentExceptRule(Rule):
    """G006 — swallowed exceptions.

    ``except Exception: <no raise>`` hid the conftest collection failure
    class of bug for five rounds; a broad handler must re-raise, convert
    to the typed ``InputError`` family, or carry a waiver saying why
    best-effort is correct (optional-dep probes, cache warming).
    """

    id = "G006"
    name = "silent-except"
    aliases = ("best-effort",)

    _BROAD = {"Exception", "BaseException"}

    def check(self, ctx, pkg):
        for node in ctx.nodes(ast.ExceptHandler):
            broad = node.type is None or (
                terminal_name(node.type) in self._BROAD
            )
            if not broad:
                continue
            raises = any(
                isinstance(sub, ast.Raise) for sub in ast.walk(node)
            )
            converts = any(
                isinstance(sub, ast.Call)
                and (terminal_name(sub.func) or "").endswith("Error")
                for sub in ast.walk(node)
            )
            if raises or converts:
                continue
            what = (
                "bare except:"
                if node.type is None
                else f"except {terminal_name(node.type)}:"
            )
            yield self.finding(
                ctx,
                node,
                f"{what} swallows without re-raise or InputError "
                "conversion; narrow it, raise, or waive with the reason "
                "best-effort is safe here",
            )


class HazardousDefaultsRule(Rule):
    """G007 — mutable defaults and import-time device work.

    A mutable default is shared across calls (stale-state bugs that only
    repro on the second run); a module-level jnp array construction
    grabs a device and compiles at import time — which on a tunneled
    TPU turns `import fastapriori_tpu` into a multi-second stall and
    breaks JAX_PLATFORMS overrides applied after import.
    """

    id = "G007"
    name = "hazardous-defaults"
    aliases = ("import-time-ok",)

    _JNP_CONSTRUCTORS = {
        "array",
        "asarray",
        "zeros",
        "ones",
        "full",
        "arange",
        "linspace",
        "eye",
        "zeros_like",
        "ones_like",
    }

    def check(self, ctx, pkg):
        for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            for default in list(fn.args.defaults) + [
                d for d in fn.args.kw_defaults if d is not None
            ]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    yield self.finding(
                        ctx,
                        default,
                        f"mutable default argument in `{fn.name}` is "
                        "shared across calls; default to None",
                    )
        # Module/class level statements only — anything inside a def is
        # deferred and fine.
        def _toplevel(body):
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(stmt, ast.ClassDef):
                    yield from _toplevel(stmt.body)
                    continue
                yield stmt

        for stmt in _toplevel(ctx.tree.body):
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted_name(node.func)
                if d is None:
                    continue
                root, _, rest = d.partition(".")
                is_jnp = root == "jnp" or d.startswith("jax.numpy.")
                if (is_jnp and node.func.attr in self._JNP_CONSTRUCTORS) or d in (
                    "jax.device_put",
                    "jax.devices",
                ):
                    yield self.finding(
                        ctx,
                        node,
                        f"module-level {d}() grabs a device backend at "
                        "import time; construct lazily inside a function",
                    )


class TodoIssueRule(Rule):
    """G008 — TODO/FIXME must reference an issue.

    An unanchored TODO is a baseline-file entry nobody ever triages;
    forcing a reference (#123, GH-123, an ISSUE/ROADMAP pointer, or a
    URL) keeps the backlog in a place that gets read.
    """

    id = "G008"
    name = "todo-issue"
    aliases = ()

    _TODO = re.compile(r"\b(TODO|FIXME|XXX)\b", re.IGNORECASE)
    _REF = re.compile(
        r"(#\d+|\bGH-\d+\b|\bISSUE\b|\bROADMAP\b|https?://)", re.IGNORECASE
    )

    def check(self, ctx, pkg):
        for line_no, comment in sorted(ctx.comments.items()):
            if self._TODO.search(comment) and not self._REF.search(comment):
                yield Finding(
                    rule=self.id,
                    path=ctx.path,
                    line=line_no,
                    col=0,
                    message=(
                        "TODO/FIXME without an issue reference "
                        "(#N, GH-N, ISSUE/ROADMAP pointer, or URL)"
                    ),
                    snippet=ctx._line(line_no),
                )


class ArtifactWriteRule(Rule):
    """G009 — artifact writes must go through the atomic writer.

    ``io/writer.py write_artifact`` is the run's output committer: tmp +
    fsync + atomic rename, a manifest entry, and the ``write.<name>``
    failpoint.  A raw open-for-write anywhere in the package bypasses
    all three — a crash mid-write can leave a torn file under the final
    name that later *parses cleanly* (the bug class ``MANIFEST.json``
    exists to catch).  Flags ``open()``/``fsspec.open()`` with a writing
    mode and any ``open_write()`` call; the committer's own internals
    carry waivers, which is the point — every bypass is an audited
    decision.  Test code is exempt (fixtures write files legitimately).
    """

    id = "G009"
    name = "artifact-write"
    aliases = ("atomic-write",)

    _WRITE_CHARS = frozenset("wax+")

    def _mode_of(self, node: ast.Call) -> Optional[str]:
        mode: Optional[ast.AST] = None
        if len(node.args) >= 2:
            mode = node.args[1]
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            return mode.value
        return None

    def check(self, ctx, pkg):
        parts = ctx.path.split("/")
        if "tests" in parts:
            return
        for node in ctx.nodes(ast.Call):
            t = terminal_name(node.func)
            if t == "open_write":
                yield self.finding(
                    ctx,
                    node,
                    "open_write() bypasses the atomic writer "
                    "(io/writer.py write_artifact): no tmp+fsync+rename, "
                    "no manifest entry, no write.<name> failpoint",
                )
            elif t == "open":
                mode = self._mode_of(node)
                if mode and (set(mode) & self._WRITE_CHARS):
                    yield self.finding(
                        ctx,
                        node,
                        f"open(..., {mode!r}) writes without the atomic "
                        "writer (io/writer.py write_artifact); route "
                        "artifacts through it, or waive stating why a "
                        "torn write is acceptable here",
                    )


# ---------------------------------------------------------------------------
# v2 flow-sensitive rules (tools/lint/{graph,flow}.py substrate): the
# remaining invariants are FLOW properties a per-node rule cannot see.


class DonationAfterUseRule(Rule):
    """G010 — donated buffers must not be referenced after the call.

    ``donate_argnums``/``donate_argnames`` frees the argument buffer at
    dispatch (the point of `parallel/mesh.py:239`'s donation is exactly
    that early free); a later reference in the same scope reads a
    deleted array — jax raises on CPU, and on a real device the error
    surfaces asynchronously, far from the bug.  One level of
    cross-function propagation: a helper that forwards its parameter to
    a donated position donates that parameter, and resolved callers
    inherit the contract (ROADMAP graftlint follow-up).
    """

    id = "G010"
    name = "donation-after-use"
    aliases = ("donate-ok",)

    def check(self, ctx, pkg):
        from tools.lint import flow

        summary = getattr(pkg, "_donating_fns", None)
        if summary is None:
            summary = flow.donating_functions(pkg.files, pkg.graph)
            pkg._donating_fns = summary
        # Fast path: a file can only have a donation-after-use if it
        # spells a donation itself or calls a known donating function
        # by name (lint wall time is CI-budgeted).
        if "donate_arg" not in ctx.source and not any(
            fq.rsplit(".", 1)[1] in ctx.source for fq in summary
        ):
            return
        for use in flow.donation_uses(ctx, pkg.graph, summary):
            yield self.finding(
                ctx,
                use.use,
                f"`{use.name}` was donated to a jitted call on line "
                f"{use.donate_line} (donate_argnums/argnames frees the "
                "buffer at dispatch) and is referenced afterwards; "
                "rebind the name or drop the donation",
            )


class ShapeBucketRule(Rule):
    """G011 — dynamic ints must be bucketed before they become shapes.

    Every distinct shape entering a traced entry point is a full XLA
    compile; VERDICT r5 measured 14 cache-miss compiles on a *primed*
    cache because data-dependent sizes escaped the pow2-bucket
    discipline.  In the dispatch layers (``parallel/``, ``models/``,
    ``rules/``), a dynamic int — ``len()``, ``.shape[...]``, ``.size``,
    arithmetic thereon — reaching a shape-forming argument
    (``zeros``/``reshape``/``pad``/``ShapeDtypeStruct``/slice sizes)
    must flow through the bucket helpers (``ops/bitmap.py next_pow2`` /
    ``pad_axis``, ``mesh.py _pad_positions``) first.  Traced function
    bodies are exempt: inside a trace, shapes are inherited from inputs
    whose bucketing was (or was flagged) at the dispatch site.
    """

    id = "G011"
    name = "shape-bucket"
    aliases = ("bucket-ok",)

    # Layers whose host code computes shapes for compiled dispatch
    # (serve/ since ISSUE 10: the serving micro-batcher forms the scan's
    # fixed compile shape from its knobs).
    scope_path_parts: Tuple[str, ...] = (
        "parallel/", "models/", "rules/", "serve/",
    )

    def check(self, ctx, pkg):
        from tools.lint import flow

        if not any(p in ctx.path for p in self.scope_path_parts):
            return
        if "tests" in ctx.path.split("/"):
            return
        summaries = getattr(pkg, "_shape_summaries", None)
        if summaries is None:
            summaries = flow.return_summaries(pkg.files, pkg.graph)
            pkg._shape_summaries = summaries
        traced = set()
        traced_fns = list(_device_functions(ctx))
        # Functions handed to jit/shard_map by NAME (the `_fn` closures
        # mesh.py builds and wraps per compile key) are traced bodies
        # too: their shapes are static per trace, keyed by the caller.
        wrapped = set()
        for node in ctx.nodes(ast.Call):
            t = terminal_name(node.func)
            if t in _JIT_NAMES or t in _SHARD_NAMES:
                for a in node.args[:1]:
                    if isinstance(a, ast.Name):
                        wrapped.add(a.id)
        for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            if fn.name in wrapped:
                traced_fns.append(fn)
        for fn in traced_fns:
            for node in ast.walk(fn):
                traced.add(id(node))
        sf = flow.ShapeFlow(ctx, pkg.graph, summaries)
        scopes = [ctx.tree.body]
        for node in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            if id(node) not in traced:
                scopes.append(node.body)
        seen = set()
        for body in scopes:
            for call, desc, state in sf.walk(body, {}):
                if state != flow.DYNAMIC or id(call) in seen:
                    continue
                if id(call) in traced:
                    continue
                seen.add(id(call))
                yield self.finding(
                    ctx,
                    call,
                    f"dynamic int reaches {desc} — every distinct value "
                    "compiles a fresh XLA program; round it through "
                    "next_pow2/pad_axis/_pad_positions (ops/bitmap.py) "
                    "first",
                )


class EnvContractRule(Rule):
    """G012 — FA_* env knobs are a strict, registered contract.

    Every knob read must (a) route through a STRICT parser — a typo'd
    value raises ``InputError`` instead of silently running a default
    on a production mine (the FA_NO_PALLAS contract, ADVICE r5 #4) —
    and (b) match an entry in the committed
    ``tools/lint/env_registry.json``, from which the README's knob
    table is rendered.  Registry entries with no remaining reference
    anywhere in the tree flag too, so the registry cannot rot.
    Strictness is detected as: the innermost enclosing function raises
    ``InputError`` itself, or calls a package function that does (one
    level of propagation — the ``parse_spec`` idiom).  Test code may
    poke knobs freely.
    """

    id = "G012"
    name = "env-contract"
    aliases = ("env-ok",)

    def _fn_raises_input_error(self, fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                t = terminal_name(
                    exc.func if isinstance(exc, ast.Call) else exc
                )
                if t == "InputError":
                    return True
        return False

    def _fn_is_strict(self, fn: ast.AST, ctx, pkg) -> bool:
        if self._fn_raises_input_error(fn):
            return True
        # One level of call-graph propagation: the read's value is
        # handed to a strict parser defined elsewhere in the package.
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            hit = pkg.graph.resolve_call(ctx, node)
            if hit is not None and self._fn_raises_input_error(hit[1]):
                return True
        return False

    def check(self, ctx, pkg):
        from tools.lint import engine as eng

        if eng.is_test_path(ctx.path):
            return
        reads = eng.env_read_sites(ctx)
        if not reads:
            return
        # Innermost enclosing function per read node (shared file-level
        # map; functions are visited breadth-first, so the deepest
        # function's assignment wins).
        enclosing = ctx.enclosing_functions()
        for name, node in reads:
            fn = enclosing.get(id(node))
            if fn is None:
                yield self.finding(
                    ctx,
                    node,
                    f"{name} read at module level — knob reads belong "
                    "inside a strict parser (InputError on typos)",
                )
            elif not self._fn_is_strict(fn, ctx, pkg):
                yield self.finding(
                    ctx,
                    node,
                    f"{name} read without a strict parse: `{fn.name}` "
                    "neither raises InputError nor calls a package "
                    "parser that does — a typo'd value silently runs "
                    "the default (the invisible-degradation class the "
                    "ledger exists to kill)",
                )
            registry = pkg.env_registry
            if registry is not None and name not in registry.get(
                "vars", {}
            ):
                yield self.finding(
                    ctx,
                    node,
                    f"{name} is not in tools/lint/env_registry.json — "
                    "add it (python -m tools.lint --write-inventory) "
                    "and describe it",
                )

    def check_package(self, pkg):
        from tools.lint import engine as eng

        registry = pkg.env_registry
        if registry is None:
            return
        refs = eng.env_var_references(pkg)
        for name in sorted(registry.get("vars", {})):
            if name not in refs:
                yield Finding(
                    rule=self.id,
                    path=eng.ENV_REGISTRY_PATH.replace("\\", "/"),
                    line=1,
                    col=0,
                    message=(
                        f"registry entry {name} has no remaining "
                        "reference in the tree — drop it "
                        "(--write-inventory) or restore the reader"
                    ),
                    snippet=name,
                )


class SiteCensusRule(Rule):
    """G013 — the audited-site inventory is unique and covered.

    The README's "audited fetch sites" claim is only checkable if the
    labels form a census: every ``retry.fetch``/``fetch_async`` site
    label and every literal ``failpoints.fire`` site must be unique
    package-wide (a duplicated label makes two link fetches
    indistinguishable in the ledger and un-armable individually), and
    every fetch label must have failpoint coverage — a literal
    ``fetch.<label>`` armed somewhere in the tree (tests /
    tools/failpoint_smoke.py) — or carry a waiver saying why injection
    cannot reach it.  Test files exercise sites, they do not define
    them, so their calls are exempt from the census.

    v3 closed the dynamic-label residue: labels resolve through
    compile-time constants (f-strings, ``+``/``%``/``.format`` over
    literals and cross-file constants, and helper-parameter flow — a
    label parameter censuses once per resolvable inflowing value), so
    a label the resolver still cannot prove is now a FINDING, not a
    silent skip: resolve it, or waive naming the site family.
    """

    id = "G013"
    name = "site-census"
    aliases = ("site-ok",)

    def check(self, ctx, pkg):
        return iter(())

    def _coverage_literals(self, pkg) -> set:
        from tools.lint import engine as eng

        return {
            value
            for value in eng.str_constant_paths(pkg)
            if "fetch." in value
        }

    def check_package(self, pkg):
        from tools.lint import engine as eng

        fetch_sites, fire_sites, _envs, unresolved = eng.site_census(pkg)
        # Blind spots: a fetch/fire label the compile-time resolver
        # cannot prove is invisible to the census (and to the
        # uniqueness/coverage checks below) — flag it where it is
        # issued.
        for kind, ctx, node in unresolved:
            yield self.finding(
                ctx,
                node,
                f"{kind} site label is not statically resolvable — the "
                "census (and its uniqueness/coverage guarantees) cannot "
                "see it; build it from compile-time constants, or waive "
                "naming the dynamic site family",
            )
        # Uniqueness: flag EVERY site of a duplicated label, so the
        # finding lands next to both spellings.
        for sites, what in ((fetch_sites, "fetch label"), (
            fire_sites, "failpoint site",
        )):
            by_label = {}
            for label, ctx, node in sites:
                by_label.setdefault(label, []).append((ctx, node))
            for label, where in sorted(by_label.items()):
                if len(where) < 2:
                    continue
                locs = ", ".join(
                    f"{c.path}:{n.lineno}" for c, n in where
                )
                for ctx, node in where:
                    yield self.finding(
                        ctx,
                        node,
                        f"{what} {label!r} is not unique package-wide "
                        f"({locs}) — duplicated labels make ledger "
                        "entries indistinguishable and failpoints "
                        "un-armable individually",
                    )
        # Coverage: every fetch label must be armable-and-armed.
        covered = self._coverage_literals(pkg)
        for label, ctx, node in fetch_sites:
            want = f"fetch.{label}"
            if any(
                c == want or (want + ":") in c for c in covered
            ):
                continue
            yield self.finding(
                ctx,
                node,
                f"fetch site {label!r} has no failpoint coverage: no "
                f"literal {want!r} is armed anywhere in the tree — add "
                "it to the fetch-site inventory test "
                "(tests/test_reliability.py) or waive with why "
                "injection cannot reach it",
            )


class SpanCensusRule(Rule):
    """G014 — every audited fetch site label has a span scope.

    The tracer instruments audited fetches centrally
    (reliability/retry.py builds the span from the dynamic site
    string), so the per-site coverage claim is only checkable through
    the declared census: ``fastapriori_tpu/obs/trace.py`` ships
    ``FETCH_SITE_SPANS``, the literal ``fetch.<label>`` list tests pin
    against real traced spans.  This rule closes the drift loop both
    ways with the G013 machinery: a fetch site added without a span
    declaration flags at the site; a declaration whose site vanished
    flags as stale.  Packages with no ``FETCH_SITE_SPANS`` assignment
    (pre-obs fixture trees) are exempt — there is no claim to check.
    """

    id = "G014"
    name = "span-census"
    aliases = ("span-ok",)

    def check(self, ctx, pkg):
        return iter(())

    def check_package(self, pkg):
        from tools.lint import engine as eng

        declared = eng.span_declarations(pkg)
        if not declared:
            return
        declared_set = {v for v, _c, _n in declared}
        fetch_sites, _fires, _envs, _blind = eng.site_census(pkg)
        live = set()
        for label, ctx, node in fetch_sites:
            want = f"fetch.{label}"
            live.add(want)
            if want in declared_set:
                continue
            yield self.finding(
                ctx,
                node,
                f"fetch site {label!r} has no span-scope declaration: "
                f"add {want!r} to FETCH_SITE_SPANS "
                "(fastapriori_tpu/obs/trace.py) so the tracer's "
                "coverage census matches the audited-fetch census",
            )
        for value, ctx, node in declared:
            if value in live:
                continue
            yield self.finding(
                ctx,
                node,
                f"span-scope declaration {value!r} is stale: no audited "
                "fetch site with that label remains — drop it from "
                "FETCH_SITE_SPANS",
            )


# ---------------------------------------------------------------------------
# v3 collective-consistency rules (tools/lint/collective.py + the rank
# taint lattice in flow.py): PR 12 bounded a divergent collective into
# PeerLost at runtime; these rules prove at lint time that no unguarded
# rank-divergent value can change a collective's shape or count.


def _rank_facts(pkg):
    """``(summaries, clamped, consensus_set)`` — the rank-taint
    fixpoint, cached per run (G015 consults it per file)."""
    from tools.lint import collective as coll
    from tools.lint import flow

    cached = getattr(pkg, "_rank_facts", None)
    if cached is None:
        consensus = coll.consensus_chain_names(pkg)
        summaries, clamped = flow.rank_summaries(
            pkg.files, pkg.graph, consensus
        )
        cached = pkg._rank_facts = (summaries, clamped, consensus)
    return cached


class DivergentCollectiveRule(Rule):
    """G015 — rank-divergent values must not steer collective dispatch.

    A branch whose test is RANK_DIVERGENT (env reads, wall-clock, RNG,
    ledger/cascade state, caught exceptions, per-rank identity — see
    flow.py's rank lattice) and whose suites issue — or reach, through
    the call graph — a mesh collective, changes WHICH or HOW MANY
    collectives this rank dispatches relative to its peers: the exact
    mesh-hang class PR 12's quorum bounds into PeerLost at runtime.
    The consensus primitives are the only sanctioned guards: a branch
    is exempt when its test consults ``stage_allowed``/``floor_stage``,
    when the divergent value came from a CONSENSUS-CLAMPED function
    (one that consults the floor itself), or when the enclosing
    function runs the consensus machinery.  Reachability stops at
    sync-clamped callees (``fit`` re-exchanges at ``mine.start`` before
    its first collective), and an except handler that re-raises or
    walks a registered cascade chain is the sanctioned divergence
    path.  The chaos harness's divergence-injection scenario
    (``tools/chaos.py --procs N``, scenario "divergence") is the
    runtime counterpart of this static guarantee.
    """

    id = "G015"
    name = "divergent-collective-guard"
    aliases = ("consensus-ok",)

    def _is_collective_call(self, node: ast.Call) -> bool:
        from tools.lint import collective as coll

        t = terminal_name(node.func)
        return t in coll.COLLECTIVE_NAMES or coll._is_multi_operand_sort(
            node
        )

    def _suite_reaches_collective(
        self, stmts, ctx, pkg, bearing
    ) -> Optional[str]:
        """A collective dispatched under these statements: directly, or
        through a graph-resolved call into the bearing closure.
        Returns a short description or None."""
        for stmt in stmts:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                if self._is_collective_call(node):
                    return f"`{terminal_name(node.func)}` on line {node.lineno}"
                fq = pkg.graph.resolve_call_fq(ctx, node)
                if fq is not None and fq in bearing:
                    return f"collective-bearing call `{fq}`"
        return None

    def check(self, ctx, pkg):
        from tools.lint import collective as coll
        from tools.lint import engine as eng
        from tools.lint import flow

        if eng.is_test_path(ctx.path):
            return
        summaries, clamped, consensus = _rank_facts(pkg)
        bearing = coll.bearing_guarded(pkg)
        if not bearing and not any(
            name in ctx.source for name in coll.COLLECTIVE_NAMES
        ):
            return
        rf = flow.RankFlow(ctx, pkg.graph, summaries, consensus)
        scopes = [ctx.tree.body]
        for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            # A scope that runs the consensus machinery anywhere is the
            # guard itself — skip it (and its nested defs are checked
            # on their own).
            if not rf.contains_sanitizer(fn):
                scopes.append(fn.body)
        seen: Set[int] = set()
        for body in scopes:
            yield from self._walk(body, rf, {}, ctx, pkg, bearing, seen)

    def _walk(self, body, rf, env, ctx, pkg, bearing, seen):
        # Path-sensitive (v4): each suite walks its own copy of the
        # taint env, worst-state merged at the join (flow.join_worst) —
        # a stage_allowed consult or consensus downgrade in one arm no
        # longer launders its sibling arm's divergent reads, and a
        # divergent read in one arm no longer taints its sibling.
        from tools.lint import flow

        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # separate scope
            if isinstance(stmt, (ast.If, ast.While)):
                state = rf.eval(stmt.test, env)
                if (
                    state == flow.RANK_DIVERGENT
                    and id(stmt) not in seen
                    and not rf.contains_sanitizer(stmt.test)
                ):
                    what = self._suite_reaches_collective(
                        stmt.body + stmt.orelse, ctx, pkg, bearing
                    )
                    if what is not None:
                        seen.add(id(stmt))
                        yield self.finding(
                            ctx,
                            stmt,
                            "rank-divergent branch steers collective "
                            f"dispatch ({what}): peers may issue "
                            "different collectives and the mesh hangs; "
                            "consult quorum.stage_allowed / exchange at "
                            "a rendezvous point first, or waive with "
                            "the lockstep argument",
                        )
                if isinstance(stmt, ast.If):
                    body_env = dict(env)
                    orelse_env = dict(env)
                    yield from self._walk(
                        stmt.body, rf, body_env, ctx, pkg, bearing, seen
                    )
                    yield from self._walk(
                        stmt.orelse, rf, orelse_env, ctx, pkg, bearing,
                        seen,
                    )
                    flow.join_worst(env, [body_env, orelse_env])
                else:  # While: body may run zero times
                    body_env = dict(env)
                    yield from self._walk(
                        stmt.body, rf, body_env, ctx, pkg, bearing, seen
                    )
                    flow.join_worst(env, [env, body_env])
                    yield from self._walk(
                        stmt.orelse, rf, env, ctx, pkg, bearing, seen
                    )
            elif isinstance(stmt, ast.For):
                rf._assign(stmt.target, rf.eval(stmt.iter, env), env)
                body_env = dict(env)
                yield from self._walk(
                    stmt.body, rf, body_env, ctx, pkg, bearing, seen
                )
                flow.join_worst(env, [env, body_env])
                yield from self._walk(
                    stmt.orelse, rf, env, ctx, pkg, bearing, seen
                )
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if item.optional_vars is not None:
                        rf._assign(
                            item.optional_vars,
                            rf.eval(item.context_expr, env),
                            env,
                        )
                yield from self._walk(
                    stmt.body, rf, env, ctx, pkg, bearing, seen
                )
            elif isinstance(stmt, ast.Try):
                body_env = dict(env)
                yield from self._walk(
                    stmt.body, rf, body_env, ctx, pkg, bearing, seen
                )
                handler_base = dict(env)
                flow.join_worst(handler_base, [env, body_env])
                handler_envs = []
                for h in stmt.handlers:
                    h_env = dict(handler_base)
                    if h.name:
                        h_env[h.name] = flow.RANK_DIVERGENT
                    raises = any(
                        isinstance(s, ast.Raise) for s in ast.walk(h)
                    )
                    if (
                        not raises
                        and id(h) not in seen
                        and not rf.contains_sanitizer(h)
                    ):
                        what = self._suite_reaches_collective(
                            h.body, ctx, pkg, bearing
                        )
                        if what is not None:
                            seen.add(id(h))
                            yield self.finding(
                                ctx,
                                h,
                                "except handler issues collectives "
                                f"({what}) on a path only the failing "
                                "rank takes; re-raise, walk a "
                                "CONSENSUS_CHAINS-registered cascade, "
                                "or waive with the lockstep argument",
                            )
                    yield from self._walk(
                        h.body, rf, h_env, ctx, pkg, bearing, seen
                    )
                    handler_envs.append(h_env)
                yield from self._walk(
                    stmt.orelse, rf, body_env, ctx, pkg, bearing, seen
                )
                flow.join_worst(env, [body_env] + handler_envs)
                yield from self._walk(
                    stmt.finalbody, rf, env, ctx, pkg, bearing, seen
                )
            else:
                rf.step(stmt, env)


class ChainConsensusRule(Rule):
    """G016 — collective-shaping cascade chains must be
    consensus-registered.

    ``watchdog.CHAINS`` is the one escalation policy; a chain whose
    downgrade changes collective shape or count is only divergence-safe
    because ``quorum.CONSENSUS_CHAINS`` carries it in the exchanged
    position vector.  This rule re-derives "collective-shaping" from
    the census: a chain walked (``stage_allowed``/``floor_stage``/
    ``propose``/``downgrade``) from a collective-bearing FUNCTION — or
    at module level of a file whose module-level code dispatches a
    collective — must appear in ``CONSENSUS_CHAINS``; registered
    chains must exist in ``CHAINS`` and still be walked somewhere.
    Attribution is function-granular (v4): v3 fell back to "any walk
    in a module that dispatches collectives anywhere", which tainted
    host-local helpers for sharing a file with device code and forced
    the module-granularity waiver family the ROADMAP names.  Both
    artifacts are parsed from the linted sources (never imported), so
    the check drift-locks the live modules both ways.  Trees declaring
    no ``CONSENSUS_CHAINS`` are exempt (pre-quorum fixtures have no
    registry to check).
    """

    id = "G016"
    name = "chain-consensus-registration"
    aliases = ("chain-ok",)

    def check(self, ctx, pkg):
        return iter(())

    def check_package(self, pkg):
        from tools.lint import collective as coll

        chains = coll.chains_decl(pkg)
        consensus = coll.consensus_decl(pkg)
        if not chains or not consensus:
            return
        bearing = coll.bearing_any(pkg)
        # Files whose MODULE-LEVEL code dispatches a collective (census
        # engine `module:<module>`): the only case where a walk outside
        # any function can sit on a collective path.
        module_level_bearing = {
            s.ctx.path
            for s in coll.census(pkg)
            if s.engine.endswith(":<module>")
        }
        walked: Dict[str, Tuple] = {}
        shaping: Dict[str, str] = {}
        for chain, wctx, node, qual in coll.chain_walk_calls(pkg):
            walked.setdefault(chain, (wctx, node))
            if chain in shaping:
                continue
            if qual:
                if qual in bearing:
                    shaping[chain] = (
                        f"walked from collective-bearing `{qual}`"
                    )
            elif wctx.path in module_level_bearing:
                shaping[chain] = (
                    "walked at module level of collective-dispatching "
                    f"{wctx.path} (line {node.lineno})"
                )
        for chain, (stages, cctx, key) in sorted(chains.items()):
            if chain in consensus or chain not in shaping:
                continue
            yield self.finding(
                cctx,
                key,
                f"cascade chain {chain!r} shapes collectives "
                f"({shaping[chain]}) but is not registered in "
                "quorum.CONSENSUS_CHAINS — a local walk of this chain "
                "diverges the mesh; register it (a protocol change: "
                "the position vector grows) or waive with the "
                "host-local/lockstep argument",
            )
        for chain, (qctx, node) in sorted(consensus.items()):
            if chain not in chains:
                yield self.finding(
                    qctx,
                    node,
                    f"CONSENSUS_CHAINS entry {chain!r} does not exist "
                    "in watchdog.CHAINS — stale registration (the "
                    "exchanged position vector carries a dead slot)",
                )
            elif chain not in walked:
                yield self.finding(
                    qctx,
                    node,
                    f"CONSENSUS_CHAINS entry {chain!r} is never walked "
                    "(no stage_allowed/propose/downgrade site remains) "
                    "— drop the registration or restore the walk",
                )


class SyncCoverageRule(Rule):
    """G017 — mid-mine re-clamp sites must be exchange-dominated.

    A ``quorum.stage_allowed`` consulted inside a loop is a MID-MINE
    re-clamp: it re-reads the consensus floor each iteration so an
    adoption lands before the next dispatch.  That only helps if the
    loop actually runs the position-vector exchange — otherwise the
    floor can never change and the re-clamp is theater while a peer's
    degradation goes unadopted until the mesh hangs.  The innermost
    enclosing loop must contain a ``quorum.sync`` call, directly or
    through one resolvable callee (``_checkpoint_levels`` carries the
    level-boundary sync in the real tree).  Start-of-phase clamps
    (outside any loop) are covered by the phase rendezvous and exempt.
    """

    id = "G017"
    name = "sync-point-coverage"
    aliases = ("sync-ok",)

    def check(self, ctx, pkg):
        from tools.lint import collective as coll
        from tools.lint import engine as eng

        if eng.is_test_path(ctx.path):
            return
        if "stage_allowed" not in ctx.source:
            return
        # Innermost enclosing loop per node: loops sorted by line; a
        # nested loop re-assigns its subtree after its parent did.
        loop_of: Dict[int, ast.AST] = {}
        loops = sorted(
            ctx.nodes(ast.For, ast.While), key=lambda n: n.lineno
        )
        for loop in loops:
            for sub in ast.walk(loop):
                if sub is not loop:
                    loop_of[id(sub)] = loop
        if not loop_of:
            return
        clamped = coll.sync_clamped(pkg)
        synced_loops: Dict[int, bool] = {}
        for node in ctx.nodes(ast.Call):
            if terminal_name(node.func) != "stage_allowed":
                continue
            loop = loop_of.get(id(node))
            if loop is None:
                continue  # start-of-phase clamp: rendezvous-covered
            ok = synced_loops.get(id(loop))
            if ok is None:
                ok = self._loop_has_sync(loop, ctx, pkg, clamped)
                synced_loops[id(loop)] = ok
            if not ok:
                yield self.finding(
                    ctx,
                    node,
                    "mid-loop stage_allowed re-clamp is not dominated "
                    "by a position-vector exchange: the enclosing loop "
                    "never runs quorum.sync (directly or via a callee), "
                    "so the consensus floor it re-reads can never move "
                    "— add the boundary sync or waive with the "
                    "lockstep argument",
                )

    def _loop_has_sync(self, loop, ctx, pkg, clamped) -> bool:
        from tools.lint import collective as coll

        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            if coll.is_sync_call(node, ctx, pkg):
                return True
            fq = pkg.graph.resolve_call_fq(ctx, node)
            if fq is not None and fq in clamped:
                return True
        return False


# ---------------------------------------------------------------------------
# v4 protocol rules (tools/lint/protocol.py): the chaos invariant —
# "byte-identical OR classified OR ledger-degraded, never a hang or
# silent corruption" — checked statically instead of sampled at runtime.


class UnclassifiedRaiseRule(Rule):
    """G018 — exceptions escaping the engine/CLI boundary must be
    classified.

    The reliability contract routes every failure through the
    classification layer: user-correctable problems become
    ``InputError`` (CLI exit 2, named cause), infrastructure failures
    become the reliability types the retry/cascade machinery
    understands, and everything else is a bug.  A raw builtin raise in
    ``cli.py``/``preprocess.py``/``models/``/``serve/``/``rules/``/
    ``io/``/``parallel/`` surfaces to the operator as an unclassified
    traceback — the chaos harness would count that run as FAIL, so the
    lint does too.  Sanctioned shapes (see protocol.unclassified_raises):
    classified types and their subclasses, bare re-raises, captured-
    variable re-raises, raises the enclosing ``try`` wraps locally into
    a classified type, classified-constructing helpers, and paths that
    record a ledger event.
    """

    id = "G018"
    name = "unclassified-raise"
    aliases = ("raise-ok",)

    def check(self, ctx, pkg):
        from tools.lint import protocol as proto

        if ctx.tree is None or not proto.is_boundary_path(ctx.path):
            return
        if "raise" not in ctx.source:
            return
        for node, spelling in proto.unclassified_raises(ctx, pkg):
            yield self.finding(
                ctx,
                node,
                f"unclassified `{spelling}` escapes the engine/CLI "
                "boundary: raise InputError (or a reliability-"
                "classified type), wrap it locally into one, or emit "
                "a ledger event on this path — an unclassified "
                "traceback is a chaos-invariant FAIL",
            )


class CascadeExhaustivenessRule(Rule):
    """G019 — downgrade walks must match the live ``CHAINS`` literal,
    forward-only, and reach the exact-fallback terminus.

    ``watchdog.downgrade`` validates chain and direction at runtime —
    on the degraded path, where a typo'd stage name surfaces as a
    SECOND failure stacked on whatever triggered the cascade.  This
    rule moves the check to lint time and adds the exhaustiveness half
    the runtime cannot see: each chain somebody downgrades must have a
    literal-edge path from some walked stage to its declared terminus
    (a dynamic ``frm`` is a from-anywhere edge — the quorum adoption
    walk starts wherever the peer's position vector says).  Chains
    declaring no stages or never downgraded are G016's department
    (registration/liveness), not this rule's.
    """

    id = "G019"
    name = "cascade-exhaustiveness"
    aliases = ("cascade-ok",)

    def check(self, ctx, pkg):
        return iter(())

    def check_package(self, pkg):
        from tools.lint import collective as coll

        chains = coll.chains_decl(pkg)
        if not chains:
            return
        edges: Dict[str, Set[Tuple[str, str]]] = {}
        wild_tos: Dict[str, Set[str]] = {}
        for chain, frm, to, wctx, node in coll.downgrade_sites(pkg):
            if chain not in chains:
                yield self.finding(
                    wctx,
                    node,
                    f"downgrade walks unregistered chain {chain!r}: "
                    "no such key in watchdog.CHAINS — at runtime this "
                    "raises on the degraded path; register the chain "
                    "or fix the name",
                )
                continue
            stages = chains[chain][0]
            bad = False
            for stage in (frm, to):
                if stage is not None and stage not in stages:
                    yield self.finding(
                        wctx,
                        node,
                        f"downgrade stage {stage!r} does not exist in "
                        f"chain {chain!r} (declared order: "
                        f"{' -> '.join(stages)}); the walk and the "
                        "CHAINS literal drifted",
                    )
                    bad = True
            if bad:
                continue
            if frm is not None and to is not None:
                if stages.index(to) <= stages.index(frm):
                    yield self.finding(
                        wctx,
                        node,
                        f"downgrade {frm!r} -> {to!r} walks chain "
                        f"{chain!r} backward (declared order: "
                        f"{' -> '.join(stages)}); cascades are "
                        "forward-only — a backward walk re-arms a "
                        "stage the watchdog already burned",
                    )
                    continue
                edges.setdefault(chain, set()).add((frm, to))
            elif to is not None:
                wild_tos.setdefault(chain, set()).add(to)
            elif frm is not None:
                # v5 value-range tracking: when every assignment to
                # the dynamic `to` resolves to a literal, the site is
                # VERIFIED against each value — multi-rung jumps count
                # as real edges and bad values flag, exactly like a
                # literal walk (closes the v4 "modeled as next-stage-
                # down" residue for resolvable sites).
                rng = _dynamic_to_range(node, wctx, pkg)
                if rng:
                    for val in sorted(rng):
                        if val not in stages:
                            yield self.finding(
                                wctx,
                                node,
                                f"dynamic downgrade target resolves "
                                f"to {val!r}, which is not a stage of "
                                f"chain {chain!r} (declared order: "
                                f"{' -> '.join(stages)}); the walk "
                                "and the CHAINS literal drifted",
                            )
                        elif stages.index(val) <= stages.index(frm):
                            yield self.finding(
                                wctx,
                                node,
                                f"dynamic downgrade target resolves "
                                f"to {val!r}, walking chain {chain!r} "
                                f"backward from {frm!r} (declared "
                                f"order: {' -> '.join(stages)}); "
                                "cascades are forward-only",
                            )
                        else:
                            edges.setdefault(chain, set()).add(
                                (frm, val)
                            )
                else:
                    # Unresolvable `to`: fall back to a step to the
                    # next stage — the weakest edge the site can mean.
                    idx = stages.index(frm)
                    if idx + 1 < len(stages):
                        edges.setdefault(chain, set()).add(
                            (frm, stages[idx + 1])
                        )
        for chain in sorted(set(edges) | set(wild_tos)):
            stages, cctx, key = chains[chain]
            if len(stages) < 2:
                continue
            reach = {stages[0]} | wild_tos.get(chain, set())
            changed = True
            while changed:
                changed = False
                for frm, to in edges.get(chain, ()):
                    if frm in reach and to not in reach:
                        reach.add(to)
                        changed = True
            if stages[-1] not in reach:
                yield self.finding(
                    cctx,
                    key,
                    f"chain {chain!r} cannot reach its exact-fallback "
                    f"terminus {stages[-1]!r} through the registered "
                    "downgrade sites: a failure mid-cascade strands "
                    "the engine on a degraded-but-not-exact stage — "
                    "add the missing downgrade edge or shrink the "
                    "declared stage order",
                )


def _stage_range(
    expr: ast.AST,
    ctx: FileContext,
    pkg: PackageContext,
    fn: Optional[ast.AST],
    depth: int,
) -> Optional[Set[str]]:
    """The set of literal strings ``expr`` can evaluate to inside
    ``fn`` — None as soon as any component stays dynamic (a partial
    range would under-claim what the site can do)."""
    if depth > 4:
        return None
    s = resolve_str(expr, ctx, pkg)
    if s is not None:
        return {s}
    if isinstance(expr, ast.IfExp):
        a = _stage_range(expr.body, ctx, pkg, fn, depth + 1)
        b = _stage_range(expr.orelse, ctx, pkg, fn, depth + 1)
        if a is not None and b is not None:
            return a | b
        return None
    if isinstance(expr, ast.Name) and fn is not None:
        rhss = []
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == expr.id:
                        rhss.append(sub.value)
            elif (
                isinstance(sub, ast.AnnAssign)
                and sub.value is not None
                and isinstance(sub.target, ast.Name)
                and sub.target.id == expr.id
            ):
                rhss.append(sub.value)
        if not rhss:
            return None
        out: Set[str] = set()
        for rhs in rhss:
            sub_range = _stage_range(rhs, ctx, pkg, fn, depth + 1)
            if sub_range is None:
                return None
            out |= sub_range
        return out
    return None


def _dynamic_to_range(
    node: ast.Call, ctx: FileContext, pkg: PackageContext
) -> Optional[Set[str]]:
    """Value range of a ``downgrade(...)`` call's dynamic ``to``."""
    to_expr = node.args[2] if len(node.args) > 2 else None
    for kw in node.keywords:
        if kw.arg == "to":
            to_expr = kw.value
    if to_expr is None:
        return None
    fn = ctx.enclosing_functions().get(id(node))
    return _stage_range(to_expr, ctx, pkg, fn, 0)


class FenceDisciplineRule(Rule):
    """G020 — fenced checkpoints, checked instead of trusted.

    PR 12's split-brain contract: a checkpoint writer acquires the
    domain fence ONCE and stamps every manifest commit with it
    (``write_manifest(..., fence=...)`` keeps it monotone); every
    resume path validates the stamp against the authoritative FENCE
    before seeding state (``quorum.validate_resume_fence``).  The
    contract only existed where checkpoint.py remembered to follow it
    — this rule makes both halves structural: fence-less manifest
    writes and validate-less manifest reads flag (protocol.
    fence_findings; tools/ and tests are out of scope — chaos reads
    manifests to check this invariant from outside it).
    """

    id = "G020"
    name = "fence-discipline"
    aliases = ("fence-ok",)

    def check(self, ctx, pkg):
        from tools.lint import protocol as proto

        if ctx.tree is None:
            return
        if not any(n in ctx.source for n in ("write_manifest",) + proto._MANIFEST_READERS):
            return
        for node, message in proto.fence_findings(ctx, pkg):
            yield self.finding(ctx, node, message)


# ---------------------------------------------------------------------------
# v5 concurrency & liveness rules (tools/lint/concurrency.py): the
# threaded serving / elastic-mesh tier's "never a hang, never a mixed
# table, never a stale epoch" contracts, checked statically.


class BoundedWaitRule(Rule):
    """G021 — every blocking primitive carries a finite bound.

    The serving dispatcher, the router's flusher/poller threads, and
    the quorum heartbeat all promise "never a hang": PR 10's chaos
    harness samples that at runtime, this rule proves the call shapes
    at lint time.  A ``.wait()`` / ``.join()`` / queue ``.get()``/
    ``.put()`` with no finite timeout, and a constant-true sleep loop
    with no break/return/raise, can park a thread forever — shutdown
    then deadlocks on ``join``.  Escape hatch (censused, not assumed):
    an unbounded wait whose enclosing function checks a module-level
    shutdown sentinel (``_STOP = object()``) that the same file
    delivers from a ``finally`` suite — the serve ring's hand-off
    shape, where delivery is guaranteed even on the crash path.
    tools/ and tests are out of scope (the chaos/CI harnesses park
    threads on purpose).
    """

    id = "G021"
    name = "bounded-wait"
    aliases = ("wait-ok",)

    def check(self, ctx, pkg):
        from tools.lint import concurrency as conc

        if (
            ctx.tree is None
            or is_test_path(ctx.path)
            or ctx.path.startswith("tools/")
        ):
            return
        src = ctx.source
        if not any(
            s in src
            for s in (".wait(", ".join(", ".get(", ".put(", "while ")
        ):
            return
        for node, message in conc.liveness_findings(ctx):
            yield self.finding(ctx, node, message)


class SharedStateRule(Rule):
    """G022 — cross-thread mutable state is lock-guarded.

    A lightweight race detector over the class/field graph: for every
    class that constructs its own ``threading.Thread``, the rule
    closes each spawn target over its ``self.X()`` call edges into a
    thread group, then flags any store to a ``self`` attribute that is
    (a) reachable from >= 2 groups and (b) not under a ``with
    self.<lock>:`` region.  ``__init__`` and the spawning methods are
    exempt (their stores happen-before ``Thread.start``), method
    CALLS are not stores (``self._ring.append`` and the allocation-
    free metrics primitives stay legal), and a helper whose every
    intra-class call site sits inside a guarded region inherits the
    caller's lock (the ``_shed_locked`` shape).  Reads are deliberately
    not flagged — the serving tier reads hot fields lock-free.
    """

    id = "G022"
    name = "shared-state-guard"
    aliases = ("race-ok",)

    def check(self, ctx, pkg):
        from tools.lint import concurrency as conc

        if (
            ctx.tree is None
            or is_test_path(ctx.path)
            or ctx.path.startswith("tools/")
        ):
            return
        if "Thread" not in ctx.source:
            return
        for node, attr, cls, n in conc.race_findings(ctx):
            yield self.finding(
                ctx,
                node,
                f"`self.{attr}` is stored here without the class lock "
                f"but is reachable from {n} thread contexts of "
                f"`{cls}` — guard the store with the lock, hand the "
                "value off through a censused ring/queue, or keep the "
                "field single-writer",
            )


class SwapBarrierRule(Rule):
    """G023 — a served model table is installed only through a barrier.

    The dispatcher's swap contract (PR 19): a new ``ServingState``
    travels the SAME ring as the work items, so the pack/scan/dispatch
    stages observe it in hand-off order and no batch is ever scored
    against a mixed table.  A direct ``self.*state = <value>``
    assignment in a thread-spawning class bypasses that ordering — the
    rule accepts only marker installs (``self._x = marker.state``, the
    ring hand-off shape) and swap-named barrier methods
    (``_commit_swap``, ``swap_all`` staging); everything else flags.
    """

    id = "G023"
    name = "swap-barrier"
    aliases = ("swap-ok",)

    def check(self, ctx, pkg):
        from tools.lint import concurrency as conc

        if (
            ctx.tree is None
            or is_test_path(ctx.path)
            or ctx.path.startswith("tools/")
        ):
            return
        if "Thread" not in ctx.source:
            return
        for node, attr, cls in conc.swap_findings(ctx):
            yield self.finding(
                ctx,
                node,
                f"served table `self.{attr}` of `{cls}` installed by "
                "direct assignment — route the install through a "
                "barrier path (ring marker / `_commit_swap` / "
                "`swap_all` staging); a direct install mid-batch "
                "serves a mixed table",
            )


class EpochNamespaceRule(Rule):
    """G024 — marker/payload paths route through the epoch/seq
    namespace.

    The elastic-mesh pairing proof is by construction: quorum markers
    live under ``e<epoch>.<site>`` (``_esite``) so a straggler from an
    aborted epoch can never be paired with the survivors' round, and
    router protocol payloads (``req-``/``rsp-``/``swap-``/
    ``swapped-``/``reset-``) carry the request seq so responses pair
    with their requests.  This rule checks both halves statically:
    every ``post_marker``/``peer_marker``/``_exchange_file`` call site
    must pass an epoch-tainted path (``_esite(...)`` or an f-string
    referencing the mesh epoch, tracked through local assignments
    across the closure chain), and every protocol payload f-string
    must interpolate a seq.  The transport helper bodies themselves
    are the sanctioned implementation and are exempt.
    """

    id = "G024"
    name = "epoch-namespace"
    aliases = ("epoch-ok",)

    def check(self, ctx, pkg):
        from tools.lint import concurrency as conc

        if (
            ctx.tree is None
            or is_test_path(ctx.path)
            or ctx.path.startswith("tools/")
            or not conc.is_proto_file(ctx.path)
        ):
            return
        for node, message in conc.epoch_findings(ctx):
            yield self.finding(ctx, node, message)


ALL_RULES: Sequence[Rule] = (
    HostSyncRule(),
    CollectiveAxisRule(),
    RecompileHazardRule(),
    DtypeDisciplineRule(),
    PallasConstraintRule(),
    SilentExceptRule(),
    HazardousDefaultsRule(),
    TodoIssueRule(),
    ArtifactWriteRule(),
    DonationAfterUseRule(),
    ShapeBucketRule(),
    EnvContractRule(),
    SiteCensusRule(),
    SpanCensusRule(),
    DivergentCollectiveRule(),
    ChainConsensusRule(),
    SyncCoverageRule(),
    UnclassifiedRaiseRule(),
    CascadeExhaustivenessRule(),
    FenceDisciplineRule(),
    BoundedWaitRule(),
    SharedStateRule(),
    SwapBarrierRule(),
    EpochNamespaceRule(),
)

RULES_BY_ID = {r.id: r for r in ALL_RULES}
