"""graftlint CLI: ``python -m tools.lint [paths...] [options]``.

Exit codes: 0 = clean (or everything frozen in the baseline), 1 = new
findings or unparsable files, 2 = usage error.  ``--write-baseline``
regenerates the freeze file from the current findings and exits 0 —
that's a deliberate ratchet-reset; reviewers should see the baseline
diff in the same PR as whatever it freezes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from tools.lint import engine
from tools.lint.rules import ALL_RULES, RULES_BY_ID

# The full linted surface (v2): the package, the test suite, the bench
# driver, the multichip entry script, and the tooling (including this
# linter — it obeys its own contracts).
DEFAULT_PATHS = [
    "fastapriori_tpu",
    "tests",
    "bench.py",
    "__graft_entry__.py",
    "tools",
]

# README block the env-knob table is rendered into (from the checked
# registry, never by hand).
_TABLE_BEGIN = "<!-- fa-env-registry:begin -->"
_TABLE_END = "<!-- fa-env-registry:end -->"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="graftlint: enforce this repo's JAX/TPU invariants",
    )
    p.add_argument(
        "paths",
        nargs="*",
        default=None,
        help=f"files/directories to lint (default: {' '.join(DEFAULT_PATHS)})",
    )
    p.add_argument(
        "--baseline",
        metavar="FILE",
        help="freeze file; only findings beyond it fail the run",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite --baseline from the current findings and exit 0",
    )
    p.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        dest="fmt",
    )
    p.add_argument(
        "--select",
        metavar="IDS",
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--root",
        default=".",
        help="repo root that relative paths (and baselines) resolve against",
    )
    p.add_argument(
        "--show-baselined",
        action="store_true",
        help="also print findings the baseline already freezes",
    )
    p.add_argument(
        "--write-inventory",
        action="store_true",
        help=(
            "regenerate tools/lint/inventory.json, env_registry.json "
            "and the README env-knob table from this run, then exit 0"
        ),
    )
    p.add_argument(
        "--check-inventory",
        action="store_true",
        help=(
            "fail (exit 1) if the committed inventory/registry/README "
            "table drift from what this run would regenerate"
        ),
    )
    p.add_argument(
        "--no-cache",
        action="store_true",
        help=(
            "skip the per-file analysis cache (tools/lint/.cache.json; "
            "mtime+size keyed, results bit-identical either way)"
        ),
    )
    return p


def _render_readme(readme: str, table: str) -> Optional[str]:
    """README text with the block between the env-registry markers
    replaced by ``table``; None when the markers are missing."""
    begin = readme.find(_TABLE_BEGIN)
    end = readme.find(_TABLE_END)
    if begin < 0 or end < 0 or end < begin:
        return None
    head = readme[: begin + len(_TABLE_BEGIN)]
    return f"{head}\n{table}{readme[end:]}"


def _inventory_artifacts(result, root: str):
    """(fresh inventory, fresh registry, fresh README text or None,
    per-artifact drift messages) for --write/--check-inventory."""
    import difflib

    drift = []
    inv_path = os.path.join(root, engine.INVENTORY_PATH)
    reg_path = os.path.join(root, engine.ENV_REGISTRY_PATH)
    readme_path = os.path.join(root, "README.md")
    try:
        with open(inv_path, "r", encoding="utf-8") as fh:
            committed_inv = json.load(fh)
    except (FileNotFoundError, json.JSONDecodeError):
        committed_inv = None
    committed_reg = engine.load_env_registry(root)
    fresh_inv = result.inventory
    fresh_reg = engine.regenerate_env_registry(result.pkg, committed_reg)
    if committed_inv != fresh_inv:
        old = json.dumps(committed_inv, indent=2, sort_keys=True)
        new = json.dumps(fresh_inv, indent=2, sort_keys=True)
        diff = "\n".join(
            list(
                difflib.unified_diff(
                    old.splitlines(),
                    new.splitlines(),
                    "committed inventory.json",
                    "regenerated",
                    lineterm="",
                )
            )[:40]
        )
        drift.append(
            f"{engine.INVENTORY_PATH} drifted from the tree:\n{diff}"
        )
    if committed_reg != fresh_reg:
        drift.append(
            f"{engine.ENV_REGISTRY_PATH} drifted (vars or readers "
            "changed); regenerate with --write-inventory and describe "
            "any new knob"
        )
    fresh_readme = None
    try:
        with open(readme_path, "r", encoding="utf-8") as fh:
            readme = fh.read()
    except FileNotFoundError:
        readme = None
    if readme is not None:
        table = engine.render_env_table(fresh_reg)
        fresh_readme = _render_readme(readme, table)
        if fresh_readme is None:
            drift.append(
                "README.md lacks the fa-env-registry markers; the knob "
                "table must be rendered from the registry, not typed"
            )
        elif fresh_readme != readme:
            drift.append(
                "README.md env-knob table drifted from the registry; "
                "regenerate with --write-inventory"
            )
    return fresh_inv, fresh_reg, fresh_readme, readme_path, drift


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    paths = args.paths or DEFAULT_PATHS

    rules = list(ALL_RULES)
    if args.select:
        wanted = [s.strip().upper() for s in args.select.split(",") if s.strip()]
        unknown = [w for w in wanted if w not in RULES_BY_ID]
        if unknown:
            print(
                f"unknown rule id(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(RULES_BY_ID))})",
                file=sys.stderr,
            )
            return 2
        rules = [RULES_BY_ID[w] for w in wanted]

    if args.write_inventory or args.check_inventory:
        if args.select:
            print(
                "--write/--check-inventory need the full rule set and "
                "the full default paths; drop --select",
                file=sys.stderr,
            )
            return 2
        # A partial-path run would regenerate (or drift-check) the
        # committed inventory from a TRUNCATED census; refuse when the
        # root holds linted files the given paths do not cover.
        full = set(engine.iter_py_files(DEFAULT_PATHS, args.root))
        given = set(engine.iter_py_files(paths, args.root))
        missing = full - given
        if missing:
            print(
                f"--write/--check-inventory need the full default "
                f"paths ({' '.join(DEFAULT_PATHS)}): {len(missing)} "
                "linted file(s) under this root are not covered by "
                f"{' '.join(paths)}",
                file=sys.stderr,
            )
            return 2

    baseline = None
    if args.baseline and not args.write_baseline:
        try:
            baseline = engine.load_baseline(args.baseline)
        except FileNotFoundError:
            baseline = None  # first run: everything is "new"
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2

    result = engine.lint_paths(
        paths,
        root=args.root,
        baseline=baseline,
        rules=rules,
        use_cache=not args.no_cache,
    )

    if args.write_inventory or args.check_inventory:
        fresh_inv, fresh_reg, fresh_readme, readme_path, drift = (
            _inventory_artifacts(result, args.root)
        )
        if args.write_inventory:
            # lint: waive G009 -- lint artifacts, not run outputs: a torn write is re-run, not parsed
            with open(
                os.path.join(args.root, engine.INVENTORY_PATH),
                "w",
                encoding="utf-8",
            ) as fh:
                json.dump(fresh_inv, fh, indent=2, sort_keys=False)
                fh.write("\n")
            # lint: waive G009 -- lint artifacts, not run outputs: a torn write is re-run, not parsed
            with open(
                os.path.join(args.root, engine.ENV_REGISTRY_PATH),
                "w",
                encoding="utf-8",
            ) as fh:
                json.dump(fresh_reg, fh, indent=2, sort_keys=False)
                fh.write("\n")
            if fresh_readme is not None:
                # lint: waive G009 -- lint artifacts, not run outputs: a torn write is re-run, not parsed
                with open(readme_path, "w", encoding="utf-8") as fh:
                    fh.write(fresh_readme)
            undescribed = [
                n
                for n, e in fresh_reg["vars"].items()
                if not e.get("description")
            ]
            print(
                f"inventory written: {len(fresh_inv['fetch_sites'])} "
                f"fetch site(s), {len(fresh_inv['failpoint_sites'])} "
                f"failpoint site(s), {len(fresh_reg['vars'])} env "
                f"knob(s), {len(fresh_inv['thread_spawns'])} thread "
                f"spawn(s), {len(fresh_inv['blocking_sites'])} "
                f"blocking site(s), {len(fresh_inv['waivers'])} "
                f"waiver(s)"
            )
            if undescribed:
                print(
                    "describe these registry entries before committing: "
                    + ", ".join(sorted(undescribed)),
                    file=sys.stderr,
                )
            return 0
        if drift:
            for msg in drift:
                print(f"inventory drift: {msg}", file=sys.stderr)
            print(
                "inventory churn must ride the PR that causes it: run "
                "`python -m tools.lint --write-inventory` and commit "
                "the result",
                file=sys.stderr,
            )
            return 1
        # fall through: --check-inventory also reports lint findings

    if args.write_baseline:
        if not args.baseline:
            print("--write-baseline requires --baseline FILE", file=sys.stderr)
            return 2
        if args.select:
            # A partial-rule rewrite would silently un-freeze every other
            # rule's fingerprints.
            print(
                "--write-baseline cannot be combined with --select: the "
                "baseline must be regenerated from the full rule set",
                file=sys.stderr,
            )
            return 2
        data = engine.make_baseline(result.findings)
        # lint: waive G009 -- the baseline is a lint artifact, not a run output; a torn write is re-run
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2, sort_keys=False)
            fh.write("\n")
        print(
            f"baseline written: {args.baseline} "
            f"({len(result.findings)} finding(s) frozen)"
        )
        return 0

    shown = result.findings if args.show_baselined else result.new_findings
    reported = list(result.parse_errors) + list(shown)
    if args.fmt == "json":
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in reported],
                    "total": len(result.findings),
                    "new": len(result.new_findings),
                    "parse_errors": len(result.parse_errors),
                },
                indent=2,
            )
        )
    else:
        for f in reported:
            print(f.format_text())
            if f.snippet:
                print(f"    {f.snippet}")
        frozen = len(result.findings) - len(result.new_findings)
        tail = (
            f"{len(result.new_findings)} new finding(s), "
            f"{frozen} baselined, {len(result.parse_errors)} parse error(s)"
        )
        print(("FAIL: " if result.failed else "OK: ") + tail)
    return 1 if result.failed else 0


if __name__ == "__main__":
    sys.exit(main())
