"""graftlint CLI: ``python -m tools.lint [paths...] [options]``.

Exit codes: 0 = clean (or everything frozen in the baseline), 1 = new
findings or unparsable files, 2 = usage error.  ``--write-baseline``
regenerates the freeze file from the current findings and exits 0 —
that's a deliberate ratchet-reset; reviewers should see the baseline
diff in the same PR as whatever it freezes.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from tools.lint import engine
from tools.lint.rules import ALL_RULES, RULES_BY_ID

DEFAULT_PATHS = ["fastapriori_tpu", "tests"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="graftlint: enforce this repo's JAX/TPU invariants",
    )
    p.add_argument(
        "paths",
        nargs="*",
        default=None,
        help=f"files/directories to lint (default: {' '.join(DEFAULT_PATHS)})",
    )
    p.add_argument(
        "--baseline",
        metavar="FILE",
        help="freeze file; only findings beyond it fail the run",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite --baseline from the current findings and exit 0",
    )
    p.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        dest="fmt",
    )
    p.add_argument(
        "--select",
        metavar="IDS",
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--root",
        default=".",
        help="repo root that relative paths (and baselines) resolve against",
    )
    p.add_argument(
        "--show-baselined",
        action="store_true",
        help="also print findings the baseline already freezes",
    )
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    paths = args.paths or DEFAULT_PATHS

    rules = list(ALL_RULES)
    if args.select:
        wanted = [s.strip().upper() for s in args.select.split(",") if s.strip()]
        unknown = [w for w in wanted if w not in RULES_BY_ID]
        if unknown:
            print(
                f"unknown rule id(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(RULES_BY_ID))})",
                file=sys.stderr,
            )
            return 2
        rules = [RULES_BY_ID[w] for w in wanted]

    baseline = None
    if args.baseline and not args.write_baseline:
        try:
            baseline = engine.load_baseline(args.baseline)
        except FileNotFoundError:
            baseline = None  # first run: everything is "new"
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2

    result = engine.lint_paths(
        paths, root=args.root, baseline=baseline, rules=rules
    )

    if args.write_baseline:
        if not args.baseline:
            print("--write-baseline requires --baseline FILE", file=sys.stderr)
            return 2
        if args.select:
            # A partial-rule rewrite would silently un-freeze every other
            # rule's fingerprints.
            print(
                "--write-baseline cannot be combined with --select: the "
                "baseline must be regenerated from the full rule set",
                file=sys.stderr,
            )
            return 2
        data = engine.make_baseline(result.findings)
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2, sort_keys=False)
            fh.write("\n")
        print(
            f"baseline written: {args.baseline} "
            f"({len(result.findings)} finding(s) frozen)"
        )
        return 0

    shown = result.findings if args.show_baselined else result.new_findings
    reported = list(result.parse_errors) + list(shown)
    if args.fmt == "json":
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in reported],
                    "total": len(result.findings),
                    "new": len(result.new_findings),
                    "parse_errors": len(result.parse_errors),
                },
                indent=2,
            )
        )
    else:
        for f in reported:
            print(f.format_text())
            if f.snippet:
                print(f"    {f.snippet}")
        frozen = len(result.findings) - len(result.new_findings)
        tail = (
            f"{len(result.new_findings)} new finding(s), "
            f"{frozen} baselined, {len(result.parse_errors)} parse error(s)"
        )
        print(("FAIL: " if result.failed else "OK: ") + tail)
    return 1 if result.failed else 0


if __name__ == "__main__":
    sys.exit(main())
