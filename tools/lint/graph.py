"""graftlint v2 package graph: symbol table + call graph (pure stdlib).

graftlint v1 rules see one AST node at a time plus a flat constant
table; the remaining repo invariants are *flow* properties (a donated
buffer referenced after the jitted call, a dynamic int reaching a shape
argument) whose sources and sinks live in different functions — and
sometimes different files.  This module builds the package-wide view the
flow rules (tools/lint/flow.py) walk:

- a **module table** per file: import bindings (``from x import y as z``
  resolves ``z`` to ``x.y``), top-level functions/methods, and the
  module's constant tables;
- a **symbol table** keyed by fully-qualified dotted name;
- a **call graph** by *terminal-name resolution*: a call is resolved
  through the file's import bindings first, and — matching the v1 rule
  convention that ``lax.psum`` and a bare ``psum`` are the same thing —
  falls back to a package-unique terminal-name match, so a renamed
  import cannot hide a callee from the flow rules;
- **cross-file constant resolution** (``from pkg.meshdef import AXIS as
  A`` resolves ``A`` to the literal), shrinking the waiver pressure on
  the constant-driven rules (G002/G004).

Resolution stays deliberately shallow beyond that: no attribute-type
inference, no dynamic dispatch.  A lint heuristic that guesses wrong
silently is worse than one that asks for a waiver.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple


def module_name(path: str) -> str:
    """``pkg/parallel/mesh.py`` -> ``pkg.parallel.mesh``;
    ``pkg/__init__.py`` -> ``pkg``."""
    p = path[:-3] if path.endswith(".py") else path
    p = p.replace("/", ".")
    if p.endswith(".__init__"):
        p = p[: -len(".__init__")]
    return p


class ModuleTable:
    """One file's contribution to the package graph."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.name = module_name(ctx.path)
        self.is_package = ctx.path.endswith("__init__.py")
        # local binding -> fully-qualified dotted target.
        self.imports: Dict[str, str] = {}
        # local (possibly Class.method) name -> FunctionDef node.
        self.functions: Dict[str, ast.AST] = {}
        if ctx.tree is not None:
            self._collect()
        # Reverse lookup: id(FunctionDef) -> fully-qualified name (the
        # v3 closures resolve calls on the hot path; a linear scan of
        # ``functions`` per resolved call does not scale).
        self.fq_by_id: Dict[int, str] = {
            id(fn): f"{self.name}.{local}"
            for local, fn in self.functions.items()
        }

    def _package(self, level: int) -> str:
        """Base package a ``level``-dot relative import resolves against."""
        base = self.name if self.is_package else self.name.rpartition(".")[0]
        for _ in range(level - 1):
            base = base.rpartition(".")[0]
        return base

    def _collect(self) -> None:
        for node in self.ctx.nodes(ast.Import, ast.ImportFrom):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.imports[a.asname] = a.name
                    else:
                        # `import a.b.c` binds the root `a`; dotted uses
                        # resolve through the longest-prefix walk below.
                        root = a.name.split(".")[0]
                        self.imports.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = self._package(node.level)
                    mod = f"{base}.{node.module}" if node.module else base
                else:
                    mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.imports[a.asname or a.name] = f"{mod}.{a.name}"
        for stmt in self.ctx.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[stmt.name] = stmt
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.functions[f"{stmt.name}.{sub.name}"] = sub

    def resolve_dotted(self, dotted: str) -> Optional[str]:
        """Local dotted reference -> fully-qualified name, through the
        import bindings (longest prefix wins) or this module's own
        top-level definitions."""
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            alias = ".".join(parts[:i])
            target = self.imports.get(alias)
            if target is not None:
                rest = parts[i:]
                return ".".join([target] + rest) if rest else target
        head = parts[0]
        if head in self.functions or head in self.ctx.str_consts or (
            head in self.ctx.int_consts
        ):
            return f"{self.name}.{dotted}"
        return None


class PackageGraph:
    """Symbol table + call graph over every linted file."""

    def __init__(self, files: Sequence):
        self.modules: Dict[str, ModuleTable] = {}
        self.by_path: Dict[str, ModuleTable] = {}
        for ctx in files:
            table = ModuleTable(ctx)
            self.modules[table.name] = table
            self.by_path[ctx.path] = table
        # Terminal function name -> fq names defining it (for the
        # unique-terminal fallback).
        self._by_terminal: Dict[str, List[str]] = {}
        for mod in self.modules.values():
            for local, fn in mod.functions.items():
                fq = f"{mod.name}.{local}"
                self._by_terminal.setdefault(
                    local.rpartition(".")[2], []
                ).append(fq)

    # -- symbol lookup ----------------------------------------------------
    def lookup_function(self, fq: str) -> Optional[Tuple[ModuleTable, ast.AST]]:
        """Fully-qualified name -> (module, FunctionDef), trying both the
        plain ``mod.fn`` and the ``mod.Class.meth`` split."""
        for cut in (1, 2):
            parts = fq.rsplit(".", cut)
            if len(parts) != cut + 1:
                continue
            mod = self.modules.get(parts[0])
            if mod is not None:
                fn = mod.functions.get(".".join(parts[1:]))
                if fn is not None:
                    return mod, fn
        return None

    def lookup_str_const(self, fq: str) -> Optional[str]:
        mod_name, _, attr = fq.rpartition(".")
        mod = self.modules.get(mod_name)
        if mod is not None:
            return mod.ctx.str_consts.get(attr)
        return None

    def lookup_int_const(self, fq: str) -> Optional[int]:
        mod_name, _, attr = fq.rpartition(".")
        mod = self.modules.get(mod_name)
        if mod is not None:
            return mod.ctx.int_consts.get(attr)
        return None

    # -- expression resolution --------------------------------------------
    def resolve_expr(self, ctx, node: ast.AST) -> Optional[str]:
        """Name/attribute-chain expression -> fully-qualified name (via
        the file's import bindings), or None."""
        from tools.lint.engine import dotted_name

        d = dotted_name(node)
        if d is None:
            return None
        table = self.by_path.get(ctx.path)
        if table is None:
            return None
        return table.resolve_dotted(d)

    def resolve_call(
        self, ctx, call: ast.Call
    ) -> Optional[Tuple[ModuleTable, ast.AST]]:
        """Resolve a call's target function: import-resolution first,
        then the package-unique terminal-name fallback."""
        from tools.lint.engine import terminal_name

        fq = self.resolve_expr(ctx, call.func)
        if fq is not None:
            hit = self.lookup_function(fq)
            if hit is not None:
                return hit
        t = terminal_name(call.func)
        if t is not None:
            candidates = self._by_terminal.get(t, [])
            if len(candidates) == 1:
                return self.lookup_function(candidates[0])
        return None

    def resolve_call_fq(self, ctx, call: ast.Call) -> Optional[str]:
        """Resolve a call straight to its target's fully-qualified
        name (the shared reverse lookup the v3 rules/closures use)."""
        hit = self.resolve_call(ctx, call)
        if hit is None:
            return None
        mod, target = hit
        return mod.fq_by_id.get(id(target))

    def callees(self, ctx, fn: ast.AST) -> Set[str]:
        """Fully-qualified names of every resolvable call in ``fn``
        (test/diagnostic surface for the call graph)."""
        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                fq = self.resolve_call_fq(ctx, node)
                if fq is not None:
                    out.add(fq)
        return out

    # -- cross-file constants ---------------------------------------------
    def resolve_str_const(self, ctx, node: ast.AST) -> Optional[str]:
        """``from pkg.meshdef import AXIS as A`` + ``A`` -> the literal
        (also handles the dotted ``meshdef.AXIS`` spelling)."""
        fq = self.resolve_expr(ctx, node)
        if fq is None:
            return None
        return self.lookup_str_const(fq)

    def resolve_int_const(self, ctx, node: ast.AST) -> Optional[int]:
        fq = self.resolve_expr(ctx, node)
        if fq is None:
            return None
        return self.lookup_int_const(fq)
