"""graftlint v5 concurrency & liveness layer (ISSUE 20).

PRs 17-19 grew the exact code the first four layers cannot see: a
two-stage pipelined dispatcher with pack/scan threads and a bounded
ring, a multi-host router speaking an atomic file protocol, and
epoch-stamped elastic-mesh collectives.  Every one carries a
hand-written "never a hang / never a mixed table / never a stale
epoch" invariant that was enforced only by tests.  This module makes
those contracts static:

- a census of thread-spawn sites, blocking primitives (``.wait`` /
  ``.join`` / queue ``.get``/``.put`` / constant-true poll loops),
  lock acquisitions, ring/queue hand-offs, shutdown-sentinel
  declarations/deliveries/checks, and quorum/router marker-path
  constructions — shipped in ``inventory.json`` under the existing
  drift gate;
- the per-file analyses behind rules G021-G024 (tools/lint/rules.py
  wraps them with scope filters; tools/lint/README.md documents the
  "why" per rule).

Heuristic boundaries, stated up front (same contract as the rest of
the linter — a heuristic that guesses wrong SILENTLY is worse than
one that asks for a waiver):

- the race analysis (G022) is class-scoped: it only models classes
  that construct a ``threading.Thread`` themselves.  Module-global
  state shared with a function-spawned thread (reliability/watchdog's
  abandon ledger) is out of scope and stays a test-enforced contract;
- reads are not flagged, only unguarded stores — a torn read of a
  Python reference is a staleness bug, not a corruption bug, and the
  serving tier deliberately reads hot fields lock-free;
- hand-off containers are recognized by name shape (``_ring``,
  ``_q``, ``pending`` ...); a deque named ``self.stuff`` is invisible
  to the census.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.lint.engine import (
    FileContext,
    PackageContext,
    is_test_path,
    terminal_name,
)

# -- name-shape vocabulary ------------------------------------------------

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_LOCKISH = re.compile(r"lock|cond|mutex|sem", re.I)
_QUEUEISH = re.compile(
    r"(^|_)(q|queue|ring|jobs|work|inbox|outbox|tasks|pending|deque)\d*$",
    re.I,
)
_THREADISH = re.compile(
    r"(^|_)(t|thread|threads|worker|workers|proc|flusher|poller)\d*$", re.I
)
_HANDOFF_OPS = {
    "append",
    "appendleft",
    "pop",
    "popleft",
    "put",
    "put_nowait",
    "get",
    "get_nowait",
}
_SLEEPISH = {"sleep", "wait"}
# File-protocol payload heads (serve/router.py + reliability/quorum.py).
_PROTO_PREFIXES = (
    "req-",
    "rsp-",
    "swap-",
    "swapped-",
    "reset-",
    "mark.",
    "hb.",
    "state.",
    "exit.",
)
# Heads whose pairing depends on the *seq* namespace (G024 part B).
_SEQ_PREFIXES = ("req-", "rsp-", "swap-", "swapped-", "reset-")
_NAMESPACED = re.compile(r"seq|epoch|rank|site", re.I)
_SEQNS = re.compile(r"seq|epoch", re.I)
_STATEISH = re.compile(r"(^|_)state$")
# Quorum marker-transport entry points; calls INSIDE these bodies are
# the sanctioned implementation, not domain call sites.
_MARKER_FNS = {"post_marker", "peer_marker", "_exchange_file"}
_SANCTIONED_FNS = _MARKER_FNS | {"_esite"}


def is_proto_file(path: str) -> bool:
    """Files speaking the marker/payload file protocol (G024 scope)."""
    base = path.rsplit("/", 1)[-1]
    return "quorum" in base or "router" in base


# -- small AST helpers ----------------------------------------------------


def _is_none(node: Optional[ast.AST]) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _is_false(node: Optional[ast.AST]) -> bool:
    return isinstance(node, ast.Constant) and node.value is False


def _timeout_kw(call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg in ("timeout", "timeout_s"):
            return kw.value
    return None


def _walk_no_nested(root: ast.AST) -> Iterator[ast.AST]:
    """Subtree walk that does not descend into nested function defs
    (a closure's body runs on whichever thread calls it later)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _blocking_kind(call: ast.Call) -> Optional[str]:
    """wait/join/get/put when the call shape can suspend the thread."""
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    recv = terminal_name(call.func.value)
    if attr == "wait":
        return "wait"
    if attr == "join":
        # ``", ".join(parts)`` / ``os.path.join(a, b)`` always pass
        # arguments; a zero-arg join is essentially always Thread.join.
        if recv is not None and _THREADISH.search(recv):
            return "join"
        if (
            not call.args
            and not call.keywords
            and recv != "path"
            and not isinstance(call.func.value, ast.Constant)
        ):
            return "join"
        return None
    if attr in ("get", "put") and recv and _QUEUEISH.search(recv):
        # A str-constant first argument is dict.get(key)/dict-shaped
        # access, not Queue.get(block, timeout) — bench's stats dicts
        # are named `queue` too.
        if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
            call.args[0].value, str
        ):
            return None
        return attr
    return None


def _call_bounded(call: ast.Call, kind: str) -> bool:
    """Does the blocking call carry a finite bound in its own shape?"""
    tkw = _timeout_kw(call)
    if kind == "wait":
        if call.args and not _is_none(call.args[0]):
            return True
        return tkw is not None and not _is_none(tkw)
    if kind == "join":
        if call.args and not _is_none(call.args[0]):
            return True
        return tkw is not None and not _is_none(tkw)
    if kind == "get":
        # Queue.get(block=False) / .get(True, timeout) / .get(timeout=t)
        if tkw is not None and not _is_none(tkw):
            return True
        if len(call.args) >= 2:
            return True
        return bool(call.args) and _is_false(call.args[0])
    if kind == "put":
        if tkw is not None and not _is_none(tkw):
            return True
        if len(call.args) >= 3:
            return True
        return len(call.args) >= 2 and _is_false(call.args[1])
    return False


def _is_lockish_expr(expr: ast.AST) -> Optional[str]:
    """`with self._lock:` / `with cond:` — the guarded-region shape."""
    t = terminal_name(expr)
    if t is not None and _LOCKISH.search(t):
        return t
    if isinstance(expr, ast.Call):
        # `with self._lock.acquire_timeout(...)`-style wrappers.
        t = terminal_name(expr.func)
        if t is not None and _LOCKISH.search(t):
            return t
    return None


# -- per-file analysis ----------------------------------------------------


class FileConcurrency:
    """Every concurrency-relevant site in one file, node-bearing (the
    serializable projection lives in :func:`file_facts`)."""

    def __init__(self) -> None:
        # (Thread(...) call, target label)
        self.spawns: List[Tuple[ast.Call, str]] = []
        # (call, kind, bound) with bound in {"timeout","sentinel","none"}
        self.blocking: List[Tuple[ast.Call, str, str]] = []
        # (while node, has break/return/raise)
        self.polls: List[Tuple[ast.While, bool]] = []
        # (with/acquire node, lock name)
        self.locks: List[Tuple[ast.AST, str]] = []
        # (call, container, op)
        self.handoffs: List[Tuple[ast.Call, str, str]] = []
        # module-level NAME = object() declarations
        self.sentinels: Dict[str, ast.Assign] = {}
        # (node, sentinel name) delivered from a `finally` suite
        self.deliveries: List[Tuple[ast.AST, str]] = []
        # (compare node, sentinel name) `is` / `is not` guards
        self.checks: List[Tuple[ast.Compare, str]] = []
        # (JoinedStr, head, namespaced) protocol payload constructions
        self.markers: List[Tuple[ast.JoinedStr, str, bool]] = []


def analyze(ctx: FileContext) -> FileConcurrency:
    """The file's concurrency sites (memoized per FileContext)."""
    cached = getattr(ctx, "_concurrency_analysis", None)
    if cached is not None:
        return cached
    a = FileConcurrency()
    ctx._concurrency_analysis = a
    if ctx.tree is None:
        return a

    # Module-level shutdown sentinels: NAME = object().
    for stmt in ctx.tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Call)
            and terminal_name(stmt.value.func) == "object"
            and not stmt.value.args
            and not stmt.value.keywords
        ):
            a.sentinels[stmt.targets[0].id] = stmt

    for call in ctx.nodes(ast.Call):
        t = terminal_name(call.func)
        if t == "Thread":
            target = "<dynamic>"
            for kw in call.keywords:
                if kw.arg == "target":
                    tn = terminal_name(kw.value)
                    if tn is not None:
                        target = tn
            a.spawns.append((call, target))
        if t == "acquire" and isinstance(call.func, ast.Attribute):
            ln = terminal_name(call.func.value)
            if ln is not None and _LOCKISH.search(ln):
                a.locks.append((call, ln))
        if isinstance(call.func, ast.Attribute):
            op = call.func.attr
            recv = terminal_name(call.func.value)
            if (
                op in _HANDOFF_OPS
                and recv is not None
                and _QUEUEISH.search(recv)
            ):
                a.handoffs.append((call, recv, op))
        kind = _blocking_kind(call)
        if kind is not None:
            bound = "timeout" if _call_bounded(call, kind) else "none"
            a.blocking.append((call, kind, bound))

    for node in ctx.nodes(ast.With):
        for item in node.items:
            name = _is_lockish_expr(item.context_expr)
            if name is not None:
                a.locks.append((node, name))

    # Sentinel deliveries (from `finally` suites) and `is` checks.
    if a.sentinels:
        for tr in ctx.nodes(ast.Try):
            for stmt in tr.finalbody:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call):
                        for arg in sub.args:
                            if (
                                isinstance(arg, ast.Name)
                                and arg.id in a.sentinels
                            ):
                                a.deliveries.append((sub, arg.id))
        for cmp in ctx.nodes(ast.Compare):
            if not any(isinstance(op, (ast.Is, ast.IsNot)) for op in cmp.ops):
                continue
            for side in [cmp.left] + list(cmp.comparators):
                if isinstance(side, ast.Name) and side.id in a.sentinels:
                    a.checks.append((cmp, side.id))

    # Constant-true poll loops: a sleep/wait-bearing `while True:` with
    # no break/return/raise can never exit — shutdown hangs.
    for node in ctx.nodes(ast.While):
        if not (
            isinstance(node.test, ast.Constant) and bool(node.test.value)
        ):
            continue
        sleeps = False
        has_exit = False
        for sub in _walk_no_nested(node):
            if isinstance(sub, ast.Call):
                st = terminal_name(sub.func)
                if st in _SLEEPISH:
                    sleeps = True
            if isinstance(sub, (ast.Break, ast.Return, ast.Raise)):
                has_exit = True
        if sleeps:
            a.polls.append((node, has_exit))

    # Upgrade unbounded waits/gets that sit on a sentinel-guaranteed
    # shutdown path: the enclosing function compares against a
    # module-level sentinel that this file delivers from a `finally`.
    delivered = {name for _n, name in a.deliveries}
    if delivered:
        enc = ctx.enclosing_functions()
        checked_by_fn: Dict[int, Set[str]] = {}
        for cmp, name in a.checks:
            if name not in delivered:
                continue
            fn = enc.get(id(cmp))
            if fn is not None:
                checked_by_fn.setdefault(id(fn), set()).add(name)
        for i, (call, kind, bound) in enumerate(a.blocking):
            if bound != "none" or kind not in ("wait", "get"):
                continue
            fn = enc.get(id(call))
            if fn is not None and checked_by_fn.get(id(fn)):
                a.blocking[i] = (call, kind, "sentinel")

    # Protocol payload-path constructions (quorum/router files only).
    if is_proto_file(ctx.path):
        for j in ctx.nodes(ast.JoinedStr):
            if not j.values or not isinstance(j.values[0], ast.Constant):
                continue
            head_lit = j.values[0].value
            if not isinstance(head_lit, str):
                continue
            head = next(
                (p for p in _PROTO_PREFIXES if head_lit.startswith(p)), None
            )
            if head is None:
                continue
            namespaced = False
            for v in j.values:
                if not isinstance(v, ast.FormattedValue):
                    continue
                for sub in ast.walk(v.value):
                    t = terminal_name(sub)
                    if t is not None and (
                        _NAMESPACED.search(t) or t in ("_esite", "_site_slug")
                    ):
                        namespaced = True
            a.markers.append((j, head, namespaced))
    return a


def file_facts(ctx: FileContext) -> dict:
    """Serializable own-bytes-only projection of :func:`analyze` —
    cached in the per-file fragments (tools/lint/cache.py schema 3) so
    warm runs skip the AST scan for the inventory censuses."""
    cached = getattr(ctx, "_concurrency_facts", None)
    if cached is not None:
        return cached
    a = analyze(ctx)
    blocking = [[k, b, n.lineno] for n, k, b in a.blocking]
    blocking += [
        ["poll", "exit" if ex else "none", w.lineno] for w, ex in a.polls
    ]
    sentinels = [
        ["decl", name, node.lineno] for name, node in a.sentinels.items()
    ]
    sentinels += [["delivery", name, n.lineno] for n, name in a.deliveries]
    sentinels += [["check", name, n.lineno] for n, name in a.checks]
    facts = {
        "spawns": [[t, n.lineno] for n, t in a.spawns],
        "blocking": blocking,
        "locks": [[name, n.lineno] for n, name in a.locks],
        "handoffs": [[c, op, n.lineno] for n, c, op in a.handoffs],
        "sentinels": sentinels,
        "markers": [
            [head, 1 if ns else 0, j.lineno] for j, head, ns in a.markers
        ],
    }
    ctx._concurrency_facts = facts
    return facts


# -- inventory censuses (drift-checked; test files excluded) --------------


def _census_files(pkg: PackageContext) -> Iterator[FileContext]:
    for ctx in pkg.files:
        if ctx.tree is None or is_test_path(ctx.path):
            continue
        yield ctx


def spawn_census(pkg: PackageContext) -> List[dict]:
    return [
        {"path": c.path, "target": t}
        for c in _census_files(pkg)
        for t, _ln in file_facts(c)["spawns"]
    ]


def blocking_census(pkg: PackageContext) -> List[dict]:
    return [
        {"path": c.path, "kind": k, "bound": b}
        for c in _census_files(pkg)
        for k, b, _ln in file_facts(c)["blocking"]
    ]


def lock_census(pkg: PackageContext) -> List[dict]:
    return [
        {"path": c.path, "lock": name}
        for c in _census_files(pkg)
        for name, _ln in file_facts(c)["locks"]
    ]


def handoff_census(pkg: PackageContext) -> List[dict]:
    return [
        {"path": c.path, "container": cont, "op": op}
        for c in _census_files(pkg)
        for cont, op, _ln in file_facts(c)["handoffs"]
    ]


def sentinel_census(pkg: PackageContext) -> List[dict]:
    return [
        {"path": c.path, "role": role, "name": name}
        for c in _census_files(pkg)
        for role, name, _ln in file_facts(c)["sentinels"]
    ]


def marker_census(pkg: PackageContext) -> List[dict]:
    return [
        {"path": c.path, "marker": head, "namespaced": bool(ns)}
        for c in _census_files(pkg)
        for head, ns, _ln in file_facts(c)["markers"]
    ]


# -- the class-scoped race model (G022 / G023) ----------------------------


class ThreadClass:
    """A class that constructs its own threads, decomposed into thread
    groups: each spawn target's method-closure (``self.X()`` edges),
    plus a "main" group for caller-thread methods.  ``__init__`` and
    the spawning methods themselves are excluded from the accounting —
    everything they touch happens-before ``Thread.start``."""

    def __init__(self, cls: ast.ClassDef, ctx: FileContext) -> None:
        self.cls = cls
        self.methods: Dict[str, ast.FunctionDef] = {
            f.name: f
            for f in cls.body
            if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.spawn_methods: Set[str] = set()
        self.lock_attrs: Set[str] = set()
        # group label -> list of fn nodes (method bodies / closures)
        self.groups: List[Tuple[str, List[ast.AST]]] = []
        self._build(ctx)

    def _build(self, ctx: FileContext) -> None:
        targets: List[Tuple[str, Optional[ast.AST]]] = []
        for mname, fn in self.methods.items():
            for sub in ast.walk(fn):
                if (
                    isinstance(sub, ast.Call)
                    and terminal_name(sub.func) == "Thread"
                ):
                    self.spawn_methods.add(mname)
                    for kw in sub.keywords:
                        if kw.arg != "target":
                            continue
                        if isinstance(
                            kw.value, ast.Attribute
                        ) and isinstance(kw.value.value, ast.Name):
                            targets.append((kw.value.attr, None))
                        elif isinstance(kw.value, ast.Name):
                            nested = next(
                                (
                                    s
                                    for s in ast.walk(fn)
                                    if isinstance(s, ast.FunctionDef)
                                    and s.name == kw.value.id
                                ),
                                None,
                            )
                            targets.append((kw.value.id, nested))
        for fn in ast.walk(self.cls):
            if isinstance(fn, ast.Assign) and isinstance(
                fn.value, ast.Call
            ):
                if terminal_name(fn.value.func) in _LOCK_CTORS:
                    for tgt in fn.targets:
                        if (
                            isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                        ):
                            self.lock_attrs.add(tgt.attr)
            if isinstance(fn, ast.With):
                for item in fn.items:
                    ce = item.context_expr
                    if (
                        isinstance(ce, ast.Attribute)
                        and isinstance(ce.value, ast.Name)
                        and ce.value.id == "self"
                        and _LOCKISH.search(ce.attr)
                    ):
                        self.lock_attrs.add(ce.attr)
        # self.X() call edges between methods.
        edges: Dict[str, Set[str]] = {m: set() for m in self.methods}
        for mname, fn in self.methods.items():
            for sub in ast.walk(fn):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == "self"
                    and sub.func.attr in self.methods
                ):
                    edges[mname].add(sub.func.attr)
        in_thread: Set[str] = set()
        for label, nested in targets:
            roots = [label] if nested is None else list(edges_of(nested, self.methods))
            members: Set[str] = set()
            frontier = [r for r in roots if r in self.methods]
            while frontier:
                m = frontier.pop()
                if m in members:
                    continue
                members.add(m)
                frontier.extend(edges[m])
            fns: List[ast.AST] = [self.methods[m] for m in sorted(members)]
            if nested is not None:
                fns.insert(0, nested)
            if fns:
                self.groups.append((label, fns))
                in_thread |= members
        main = [
            self.methods[m]
            for m in sorted(self.methods)
            if m not in in_thread
            and m not in self.spawn_methods
            and m != "__init__"
        ]
        if main:
            self.groups.append(("<main>", main))


def edges_of(fn: ast.AST, methods: Dict[str, ast.FunctionDef]) -> Set[str]:
    """``self.X()`` targets referenced from a closure body."""
    out: Set[str] = set()
    for sub in ast.walk(fn):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and isinstance(sub.func.value, ast.Name)
            and sub.func.value.id == "self"
            and sub.func.attr in methods
        ):
            out.add(sub.func.attr)
    return out


def thread_classes(ctx: FileContext) -> List[ThreadClass]:
    cached = getattr(ctx, "_thread_classes", None)
    if cached is not None:
        return cached
    out = []
    for cls in ctx.nodes(ast.ClassDef):
        if any(
            isinstance(sub, ast.Call)
            and terminal_name(sub.func) == "Thread"
            for sub in ast.walk(cls)
        ):
            out.append(ThreadClass(cls, ctx))
    ctx._thread_classes = out
    return out


def _guarded_ids(fn: ast.AST, lock_attrs: Set[str]) -> Set[int]:
    """ids of nodes inside a `with self.<lock>:` region of ``fn``."""

    def lockish(expr: ast.AST) -> bool:
        t = terminal_name(expr)
        if t in lock_attrs:
            return True
        return t is not None and _LOCKISH.search(t) is not None

    guarded: Set[int] = set()

    def rec(node: ast.AST, g: bool) -> None:
        for child in ast.iter_child_nodes(node):
            cg = g or (
                isinstance(child, ast.With)
                and any(lockish(i.context_expr) for i in child.items)
            )
            if cg:
                guarded.add(id(child))
            rec(child, cg)

    rec(fn, False)
    return guarded


def _self_root(node: ast.AST) -> Optional[str]:
    """`self.X`, `self.X[i]`, `self.X[i][j]` ... -> "X"."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _fn_accesses(fn: ast.AST):
    """(stores, loads) of self-attributes in one function body.
    stores: [(attr, anchor node, value expr | None)]; loads: {attr}."""
    stores: List[Tuple[str, ast.AST, Optional[ast.AST]]] = []
    loads: Set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Assign):
            for tgt in sub.targets:
                elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                for e in elts:
                    attr = _self_root(e)
                    if attr is not None:
                        stores.append((attr, sub, sub.value))
        elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
            attr = _self_root(sub.target)
            if attr is not None:
                stores.append((attr, sub, None))
        elif isinstance(sub, ast.Attribute) and isinstance(
            sub.ctx, ast.Load
        ):
            if isinstance(sub.value, ast.Name) and sub.value.id == "self":
                loads.add(sub.attr)
    return stores, loads


def race_findings(ctx: FileContext):
    """G022 core: unguarded stores to attributes reachable from >= 2
    thread groups of a thread-spawning class.  Yields
    ``(anchor node, attr, class name, n_groups)``."""
    for tc in thread_classes(ctx):
        if len(tc.groups) < 2:
            continue
        guarded: Dict[int, Set[int]] = {}
        per_group: List[Dict[str, list]] = []
        attr_groups: Dict[str, Set[int]] = {}
        for gi, (_label, fns) in enumerate(tc.groups):
            acc: Dict[str, list] = {}
            for fn in fns:
                guarded[id(fn)] = _guarded_ids(fn, tc.lock_attrs)
                stores, loads = _fn_accesses(fn)
                for attr, node, _val in stores:
                    acc.setdefault(attr, []).append((node, fn))
                    attr_groups.setdefault(attr, set()).add(gi)
                for attr in loads:
                    attr_groups.setdefault(attr, set()).add(gi)
            per_group.append(acc)
        # k=1 caller-context: a helper whose every intra-class call
        # site is inside a guarded region inherits the caller's lock
        # (serve/server.py's `_shed_locked` shape).
        lock_context: Set[str] = set()
        all_fns = [fn for _l, fns in tc.groups for fn in fns]
        for mname, m in tc.methods.items():
            sites = []
            for fn in all_fns:
                for sub in ast.walk(fn):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id == "self"
                        and sub.func.attr == mname
                    ):
                        sites.append((sub, fn))
            if sites and all(
                id(call) in guarded.get(id(fn), ()) for call, fn in sites
            ):
                lock_context.add(mname)
        seen_nodes: Set[int] = set()
        for gi, acc in enumerate(per_group):
            for attr, nodes in sorted(acc.items()):
                if attr in tc.lock_attrs:
                    continue
                if len(attr_groups.get(attr, ())) < 2:
                    continue
                for node, fn in nodes:
                    if id(node) in seen_nodes:
                        continue  # a method shared by two groups
                    if id(node) in guarded.get(id(fn), ()):
                        continue
                    fname = getattr(fn, "name", "")
                    if fname in lock_context:
                        continue
                    seen_nodes.add(id(node))
                    yield node, attr, tc.cls.name, len(
                        attr_groups[attr]
                    )


def swap_findings(ctx: FileContext):
    """G023 core: direct installs of a served table (``self.*state``)
    outside a barrier path, in a thread-spawning class.  Yields
    ``(anchor node, attr, class name)``."""
    for tc in thread_classes(ctx):
        for mname, fn in sorted(tc.methods.items()):
            if mname == "__init__" or mname in tc.spawn_methods:
                continue
            stores, _loads = _fn_accesses(fn)
            for attr, node, value in stores:
                if not _STATEISH.search(attr):
                    continue
                if "swap" in mname:
                    continue
                if (
                    isinstance(value, ast.Attribute)
                    and value.attr == "state"
                ):
                    continue  # marker install: `self._x = marker.state`
                yield node, attr, tc.cls.name


# -- the epoch/seq namespace model (G024) ---------------------------------


def _fn_parents(ctx: FileContext) -> Dict[int, ast.AST]:
    """FunctionDef -> lexically enclosing FunctionDef (closure chain)."""
    cached = getattr(ctx, "_fn_parents", None)
    if cached is not None:
        return cached
    parents: Dict[int, ast.AST] = {}
    for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
        for sub in ast.walk(fn):
            if sub is not fn and isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                # BFS order: deeper enclosing fns overwrite shallower.
                parents[id(sub)] = fn
    ctx._fn_parents = parents
    return parents


def _expr_epoch_tainted(
    expr: ast.AST, tainted: Set[str], depth: int = 0
) -> bool:
    if depth > 4:
        return False
    if isinstance(expr, ast.Call):
        return terminal_name(expr.func) == "_esite"
    if isinstance(expr, ast.JoinedStr):
        return any(
            _expr_epoch_tainted(v.value, tainted, depth + 1)
            for v in expr.values
            if isinstance(v, ast.FormattedValue)
        )
    if isinstance(expr, ast.FormattedValue):
        return _expr_epoch_tainted(expr.value, tainted, depth + 1)
    if isinstance(expr, ast.Name):
        return expr.id in tainted or "epoch" in expr.id.lower()
    if isinstance(expr, ast.Attribute):
        return "epoch" in expr.attr.lower()
    if isinstance(expr, ast.BinOp):
        return _expr_epoch_tainted(
            expr.left, tainted, depth + 1
        ) or _expr_epoch_tainted(expr.right, tainted, depth + 1)
    return False


def epoch_findings(ctx: FileContext):
    """G024 core.  Part A: quorum marker-transport calls whose site
    argument is not provably namespaced by the mesh epoch (via
    ``_esite`` or an epoch-tainted f-string, tracked through local
    assignments across the closure chain).  Part B: router protocol
    payload names built without a sequence number.  Yields
    ``(node, message)``."""
    if not is_proto_file(ctx.path):
        return
    enc = ctx.enclosing_functions()
    parents = _fn_parents(ctx)
    for call in ctx.nodes(ast.Call):
        t = terminal_name(call.func)
        if t not in _MARKER_FNS or not call.args:
            continue
        fn = enc.get(id(call))
        if fn is not None and fn.name in _SANCTIONED_FNS:
            continue
        # Assignments visible from the call: the enclosing function
        # plus its closure chain (quorum's `post_join` shape).
        chain = []
        cur = fn
        while cur is not None and len(chain) < 6:
            chain.append(cur)
            cur = parents.get(id(cur))
        assigns: Dict[str, List[ast.AST]] = {}
        for f in chain:
            for sub in ast.walk(f):
                if isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            assigns.setdefault(tgt.id, []).append(
                                sub.value
                            )
        tainted: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for name, rhss in assigns.items():
                if name in tainted:
                    continue
                if any(_expr_epoch_tainted(r, tainted) for r in rhss):
                    tainted.add(name)
                    changed = True
        if not _expr_epoch_tainted(call.args[0], tainted):
            yield call, (
                f"`{t}(...)` site is not namespaced by the mesh epoch "
                "— route it through `_esite(...)` so an elastic-mesh "
                "straggler can never pair with a stale epoch's marker"
            )
    a = analyze(ctx)
    for j, head, _ns in a.markers:
        if head not in _SEQ_PREFIXES:
            continue
        fn = enc.get(id(j))
        if fn is not None and fn.name in _SANCTIONED_FNS:
            continue
        seq_ns = False
        for v in j.values:
            if not isinstance(v, ast.FormattedValue):
                continue
            for sub in ast.walk(v.value):
                tn = terminal_name(sub)
                if tn is not None and _SEQNS.search(tn):
                    seq_ns = True
        if not seq_ns:
            yield j, (
                f'protocol payload name `f"{head}..."` carries no '
                "sequence number — req/rsp/swap pairing relies on the "
                "seq namespace"
            )


# -- bounded-wait findings (G021) -----------------------------------------


def liveness_findings(ctx: FileContext):
    """G021 core: blocking calls with no finite bound and no censused
    sentinel path, plus inescapable poll loops.  Yields
    ``(node, message)``."""
    a = analyze(ctx)
    for call, kind, bound in a.blocking:
        if bound != "none":
            continue
        yield call, (
            f"unbounded blocking `.{kind}(...)` — pass a finite "
            "timeout, or gate the loop on a module-level shutdown "
            "sentinel delivered from a `finally` suite"
        )
    for node, has_exit in a.polls:
        if has_exit:
            continue
        yield node, (
            "constant-true poll loop with no break/return/raise — "
            "this thread can never observe shutdown"
        )
