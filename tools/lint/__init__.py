"""graftlint — project-native static analysis for the JAX/TPU invariants
this codebase's performance tricks depend on (see tools/lint/README.md).

Pure stdlib (``ast`` + ``tokenize``); importing this package must never
import jax — the linter has to run in seconds on a box with no
accelerator runtime at all.
"""

from tools.lint.engine import (  # noqa: F401
    Finding,
    LintResult,
    lint_paths,
    lint_sources,
)
from tools.lint.rules import ALL_RULES  # noqa: F401

__all__ = ["Finding", "LintResult", "lint_paths", "lint_sources", "ALL_RULES"]
