"""graftlint v4: the reliability-protocol verifier (ISSUE 16).

The chaos harness (PR 9/12) SAMPLES the repo's reliability invariant at
runtime — "byte-identical OR classified-naming-the-site OR
ledger-degraded, never a hang or silent corruption".  This layer PROVES
the protocol's static half: three drift-checked censuses (every
``raise`` site, every ledger-event emission, every ``CHAINS`` walk)
ship in ``tools/lint/inventory.json``, and three rules check the
contracts the censuses witness:

- **G018 unclassified-raise** — an exception escaping an engine/CLI
  boundary surface (``cli.py``/``preprocess.py``, ``models/``,
  ``serve/``, ``rules/``, ``io/``, ``parallel/``) must be CLASSIFIED:
  an ``InputError`` (or any class defined by the classification layer —
  ``errors.py`` / ``reliability/`` — or a subclass thereof), a bare
  re-raise, a raise the enclosing ``try`` wraps locally into a
  classified type, a raise built by a classified-constructing helper
  (the ``_closure_error`` pattern), or a path that records a ledger
  event.  Everything else surfaces to the operator as an unclassified
  traceback — exactly what the chaos invariant forbids.

- **G019 cascade-exhaustiveness** — every literal ``downgrade(chain,
  frm, to)`` walk must name a ``CHAINS``-registered chain and move
  FORWARD along its declared stage order, and every chain somebody
  downgrades must have a literal-edge path to its exact-fallback
  terminus (a dynamic ``frm`` counts as a from-anywhere edge: the
  quorum adoption walk starts wherever the peer's position says).  A
  ``downgrade`` whose stages don't match the live ``CHAINS`` literal
  flags both ways — site against declaration and declaration against
  sites.

- **G020 fence-discipline** — the split-brain contract from PR 12,
  checked instead of trusted: every ``write_manifest`` call stamps the
  fence epoch (third positional or ``fence=``), and every function
  reading a manifest (``load_manifest``/``manifest_fence``) outside
  the test/tools harness validates it (``validate_resume_fence``,
  directly or through one resolvable callee — ``load_checkpoint``'s
  shape).

Like graph/flow/collective, this is pure stdlib over the parsed
sources: the linter never imports the package.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.lint.engine import (
    dotted_name,
    is_test_path,
    resolve_label,
    terminal_name,
)

# Path components that form the engine/CLI boundary: an exception
# escaping THESE surfaces reaches the operator (or a serving client)
# and must be classified.  reliability/ and obs/ ARE the
# classification/observation layers; ops/, utils/ and native/ surface
# only through the boundary modules above them.
_BOUNDARY_DIRS = {"io", "serve", "rules", "parallel", "models"}
_BOUNDARY_FILES = {"cli.py", "preprocess.py"}

# Builtin exception names an unclassified raise typically spells.
_BUILTIN_EXCEPTIONS = {
    "ArithmeticError",
    "AssertionError",
    "AttributeError",
    "BaseException",
    "BufferError",
    "EOFError",
    "Exception",
    "FileExistsError",
    "FileNotFoundError",
    "IOError",
    "IndexError",
    "KeyError",
    "LookupError",
    "MemoryError",
    "NotImplementedError",
    "OSError",
    "OverflowError",
    "PermissionError",
    "RuntimeError",
    "StopIteration",
    "TimeoutError",
    "TypeError",
    "UnicodeDecodeError",
    "ValueError",
    "ZeroDivisionError",
}


def is_boundary_path(path: str) -> bool:
    parts = path.split("/")
    if is_test_path(path) or "tools" in parts:
        return False
    return bool(_BOUNDARY_DIRS.intersection(parts[:-1])) or (
        parts[-1] in _BOUNDARY_FILES
    )


def classified_classes(pkg) -> Set[str]:
    """Names of CLASSIFIED exception classes: everything defined by the
    classification layer (``errors.py`` or any ``reliability/`` module)
    plus package classes that subclass one (terminal-name bases, to a
    fixpoint) — ``InputError``, ``StaleFenceError``, ``PeerLost``,
    ``MeshDivergence``, the watchdog timeouts, ``InjectedAbort`` in the
    real tree.  Cached per run."""
    cached = getattr(pkg, "_classified_classes", None)
    if cached is not None:
        return cached
    seed: Set[str] = set()
    bases: Dict[str, Set[str]] = {}
    for ctx in pkg.files:
        if ctx.tree is None or is_test_path(ctx.path):
            continue
        parts = ctx.path.split("/")
        classifying = "reliability" in parts or parts[-1] == "errors.py"
        for node in ctx.nodes(ast.ClassDef):
            names = {terminal_name(b) for b in node.bases}
            names.discard(None)
            bases.setdefault(node.name, set()).update(names)
            if classifying:
                seed.add(node.name)
    changed = True
    while changed:
        changed = False
        for name, base_names in bases.items():
            if name not in seed and base_names & seed:
                seed.add(name)
                changed = True
    pkg._classified_classes = seed
    return seed


def package_class_names(pkg) -> Set[str]:
    """Every class name defined in a non-test package file (an
    UNCLASSIFIED local exception type is as much a G018 finding as a
    builtin)."""
    cached = getattr(pkg, "_package_class_names", None)
    if cached is not None:
        return cached
    out: Set[str] = set()
    for ctx in pkg.files:
        if ctx.tree is None or is_test_path(ctx.path):
            continue
        for node in ctx.nodes(ast.ClassDef):
            out.add(node.name)
    pkg._package_class_names = out
    return out


# ---------------------------------------------------------------------------
# per-file protocol facts (own-bytes only — cacheable, tools/lint/cache.py)


def _is_ledger_record(call: ast.Call) -> bool:
    """The dotted ``ledger.record`` spelling (``LEDGER.record`` inside
    the ledger module itself) — the repo's one way to emit a
    degradation event.  Own-bytes decidable: the census must stay
    cacheable per file."""
    d = dotted_name(call.func)
    return d is not None and d.lower().endswith("ledger.record")


def raise_spelling(node: ast.Raise) -> str:
    """The censused spelling of a raise site: the raised class's
    terminal name, ``<reraise>`` for a bare ``raise``, ``<value>`` for
    a raised non-call expression (a captured exception variable)."""
    exc = node.exc
    if exc is None:
        return "<reraise>"
    if isinstance(exc, ast.Call):
        t = terminal_name(exc.func)
        return t if t is not None else "<dynamic>"
    t = terminal_name(exc)
    return f"<value:{t}>" if t is not None else "<value>"


def file_raises(ctx) -> List[Tuple[str, int]]:
    """``[(spelling, lineno)]`` for every raise statement in this file,
    derived from its own bytes only.  A cached fragment pre-installs
    the list (``ctx._protocol_raises``); results are bit-identical
    either way (pinned by tests)."""
    cached = getattr(ctx, "_protocol_raises", None)
    if cached is not None:
        return cached
    out = [
        (raise_spelling(node), node.lineno)
        for node in ctx.nodes(ast.Raise)
    ]
    ctx._protocol_raises = out
    return out


def file_ledger_events(ctx) -> List[Tuple[str, int]]:
    """``[(kind, lineno)]`` for every ``ledger.record`` emission in this
    file; the kind is the compile-time-resolved first argument (file
    scope only, so the fact stays own-bytes cacheable) or
    ``<dynamic>``.  A cached fragment pre-installs the list
    (``ctx._protocol_ledger``)."""
    cached = getattr(ctx, "_protocol_ledger", None)
    if cached is not None:
        return cached
    out: List[Tuple[str, int]] = []
    for node in ctx.nodes(ast.Call):
        if not _is_ledger_record(node):
            continue
        kind: Optional[str] = None
        if node.args:
            kind = resolve_label(node.args[0], ctx, None)
        out.append((kind if kind is not None else "<dynamic>", node.lineno))
    ctx._protocol_ledger = out
    return out


# ---------------------------------------------------------------------------
# package censuses (inventory artifacts — drift-checked like the
# fetch/failpoint/collective censuses)


def raise_census(pkg) -> List[dict]:
    """``raise_sites`` inventory entries over every non-test file."""
    out = []
    for ctx in pkg.files:
        if ctx.tree is None or is_test_path(ctx.path):
            continue
        for spelling, _line in file_raises(ctx):
            out.append({"exception": spelling, "path": ctx.path})
    return out


def ledger_census(pkg) -> List[dict]:
    """``ledger_events`` inventory entries over every non-test file."""
    out = []
    for ctx in pkg.files:
        if ctx.tree is None or is_test_path(ctx.path):
            continue
        for kind, _line in file_ledger_events(ctx):
            out.append({"kind": kind, "path": ctx.path})
    return out


def chain_walk_census(pkg) -> List[dict]:
    """``chain_walks`` inventory entries: every resolvable
    ``stage_allowed``/``floor_stage``/``propose``/``downgrade`` walk
    with its walker (function-granular — the v4 attribution G016 flags
    on)."""
    from tools.lint import collective as coll

    out = []
    for chain, wctx, _node, qual in coll.chain_walk_calls(pkg):
        out.append(
            {
                "chain": chain,
                "walker": qual or "<module>",
                "path": wctx.path,
            }
        )
    return out


# ---------------------------------------------------------------------------
# G018 support: local-wrap and helper-classification predicates


def _handler_catch_names(try_node: ast.Try) -> Set[str]:
    """Terminal names the handlers of this try catch; ``<bare>`` for a
    typeless handler."""
    out: Set[str] = set()
    for h in try_node.handlers:
        if h.type is None:
            out.add("<bare>")
            continue
        types = (
            h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        )
        for t in types:
            name = terminal_name(t)
            if name is not None:
                out.add(name)
    return out


def locally_wrapped_raises(ctx) -> Dict[int, Set[str]]:
    """``id(raise-node) -> union of catch names`` for every raise
    sitting in the BODY of a try whose handlers could catch it (the
    wrap idiom: ``raise ValueError`` inside ``try: ... except
    (ValueError, KeyError): raise InputError(...)``).  Handler and
    orelse raises are NOT wrapped — Python only routes body exceptions
    to the handlers."""
    out: Dict[int, Set[str]] = {}
    for try_node in ctx.nodes(ast.Try):
        catches = _handler_catch_names(try_node)
        if not catches:
            continue
        for stmt in try_node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Raise):
                    out.setdefault(id(sub), set()).update(catches)
    return out


# v5: the helper walks are k-bounded instead of one-hop — a helper
# that delegates construction/validation through one or two more
# layers of indirection resolves instead of demanding a waiver, while
# anything deeper still flags (an unbounded walk would turn a lint
# pass into a whole-program analysis; 3 hops covers every shape this
# codebase writes and the fixture tests pin the 4-hop flag).
K_HOPS = 3


def _k_reachable(start_ctx, start_fn, pkg, hops: int):
    """``[(ctx, fn)]`` reachable from ``start_fn`` through at most
    ``hops`` graph-resolvable call edges (BFS, id-deduplicated,
    memoized per package — G018 and G020 share the walks)."""
    memo = getattr(pkg, "_khop_memo", None)
    if memo is None:
        memo = pkg._khop_memo = {}
    key = (id(start_fn), hops)
    if key in memo:
        return memo[key]
    seen = {id(start_fn)}
    out = [(start_ctx, start_fn)]
    frontier = [(start_ctx, start_fn)]
    for _ in range(hops):
        nxt = []
        for fctx, fn in frontier:
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                hit = pkg.graph.resolve_call(fctx, call)
                if hit is not None and id(hit[1]) not in seen:
                    seen.add(id(hit[1]))
                    # resolve_call returns (ModuleTable, fn); walks
                    # continue in the callee's own file context.
                    pair = (hit[0].ctx, hit[1])
                    nxt.append(pair)
                    out.append(pair)
        if not nxt:
            break
        frontier = nxt
    memo[key] = out
    return out


def _fn_constructs_classified(fn: ast.AST, classified: Set[str]) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and terminal_name(
            node.func
        ) in classified:
            return True
    return False


def _fn_records_ledger(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and _is_ledger_record(node):
            return True
    return False


def unclassified_raises(ctx, pkg) -> List[Tuple[ast.Raise, str]]:
    """``[(raise-node, spelling)]`` for this boundary file's raises of
    unclassified types with no sanctioned escape: not locally wrapped,
    not built by a classified-constructing helper, no ledger event on
    the enclosing function's paths."""
    classified = classified_classes(pkg)
    pkg_classes = package_class_names(pkg)
    wrapped = None
    enclosing = None
    out: List[Tuple[ast.Raise, str]] = []
    for node in ctx.nodes(ast.Raise):
        exc = node.exc
        if exc is None:
            continue  # bare re-raise: the original classification holds
        spelling = terminal_name(
            exc.func if isinstance(exc, ast.Call) else exc
        )
        if spelling is None or spelling in classified:
            continue
        if isinstance(exc, ast.Call):
            if (
                spelling not in _BUILTIN_EXCEPTIONS
                and spelling not in pkg_classes
            ):
                # An unresolvable constructor: maybe a classified-
                # constructing helper (`raise _closure_error(...)`),
                # possibly delegating through up to K_HOPS layers.
                hit = pkg.graph.resolve_call(ctx, exc)
                if hit is not None and any(
                    _fn_constructs_classified(f, classified)
                    for _fctx, f in _k_reachable(
                        hit[0].ctx, hit[1], pkg, K_HOPS - 1
                    )
                ):
                    continue
                if hit is None:
                    continue  # external/unknown callable: not provable
        else:
            # `raise exc` of a captured variable re-raises whatever was
            # classified upstream; only a NAMED exception class counts.
            if spelling not in _BUILTIN_EXCEPTIONS and (
                spelling not in pkg_classes
            ):
                continue
        if wrapped is None:
            wrapped = locally_wrapped_raises(ctx)
        catches = wrapped.get(id(node), set())
        if (
            spelling in catches
            or "Exception" in catches
            or "BaseException" in catches
            or "<bare>" in catches
        ):
            continue
        if enclosing is None:
            enclosing = ctx.enclosing_functions()
        fn = enclosing.get(id(node))
        if fn is not None and _fn_records_ledger(fn):
            continue
        out.append((node, spelling))
    return out


# ---------------------------------------------------------------------------
# G020 support


def _call_has_fence(call: ast.Call) -> bool:
    if len(call.args) >= 3:
        return True
    return any(kw.arg == "fence" for kw in call.keywords)


def _fn_validates_fence(fn: ast.AST, ctx, pkg) -> bool:
    """``validate_resume_fence`` reached from ``fn`` within K_HOPS
    graph-resolvable call edges (``load_checkpoint`` funnels the check
    through ``quorum.validate_resume_fence`` directly; a wrapper two
    or three hops up still counts — v5 k-bounded walk)."""
    for fctx, f in _k_reachable(ctx, fn, pkg, K_HOPS):
        for node in ast.walk(f):
            if isinstance(node, ast.Call) and terminal_name(
                node.func
            ) == "validate_resume_fence":
                return True
    return False


_MANIFEST_READERS = ("load_manifest", "manifest_fence")


def fence_findings(ctx, pkg):
    """``[(node, message)]`` for this file's fence-discipline breaks:
    fence-less ``write_manifest`` calls, and manifest-reading functions
    that never validate the resume fence.  Test files and the tools/
    harness are out of scope (chaos READS manifests to check this very
    invariant from outside the protocol)."""
    parts = ctx.path.split("/")
    if is_test_path(ctx.path) or "tools" in parts:
        return []
    out = []
    enclosing = None
    checked_fns: Dict[int, bool] = {}
    for node in ctx.nodes(ast.Call):
        t = terminal_name(node.func)
        if t == "write_manifest":
            if not _call_has_fence(node):
                out.append(
                    (
                        node,
                        "manifest write does not stamp the fence epoch: "
                        "pass fence=quorum.checkpoint_fence() or None "
                        "(the split-brain contract: a superseded writer "
                        "must be rejected at commit, not trusted)",
                    )
                )
        elif t in _MANIFEST_READERS:
            if enclosing is None:
                enclosing = ctx.enclosing_functions()
            fn = enclosing.get(id(node))
            if fn is None:
                continue  # module-level read: no resume path to hold
            if fn.name in _MANIFEST_READERS:
                continue  # the primitive itself (or a fixture twin)
            ok = checked_fns.get(id(fn))
            if ok is None:
                ok = _fn_validates_fence(fn, ctx, pkg)
                checked_fns[id(fn)] = ok
            if not ok:
                out.append(
                    (
                        node,
                        f"resume path `{fn.name}` reads the manifest "
                        "but never validates the fence epoch: call "
                        "quorum.validate_resume_fence (directly or via "
                        "a callee) so a split-brain checkpoint cannot "
                        "seed a resume",
                    )
                )
    return out
