"""graftlint rule engine: file contexts, waivers, baseline, and the runner.

The engine is deliberately dumb about JAX semantics — each rule
(tools/lint/rules.py) encodes ONE contract of this codebase and gets a
parsed view of every file plus a package-wide symbol table (declared mesh
axis names, module-level string/int constants).  Everything here is
stdlib-only; the linter must run on machines with no JAX installed.

Waiver syntax (the audit trail the rules exist to force):

    x = np.asarray(counts_dev)  # lint: fetch-site -- end-of-mine fetch
    except Exception:  # lint: waive G006 -- optional-dep probe

A ``# lint:`` comment on the flagged line or the line directly above it
waives matching rules on that line.  Tokens are either a rule id
(``G001``) after the word ``waive``, or a rule's named alias
(``fetch-site``); anything after ``--`` is the human justification and is
ignored by the matcher (but reviewers should insist on it).  Three
grammar refinements pinned by tests (v2): a comment above a DECORATOR
attaches to the decorated ``def``/``class`` line (findings anchor
there); several ``lint:`` segments may be stacked in one comment
(``# lint: fetch-site -- x; lint: waive G004 -- y``) and all match; and
a waiver anywhere inside a multi-line statement binds to the flagged
node's span, so the comment can sit on the argument it is about.

Baselines freeze pre-existing findings so the CLI only fails on NEW ones:
a finding's fingerprint is ``rule|path|stripped-source-line`` (line
numbers excluded on purpose — unrelated edits must not un-freeze a
baselined finding), stored with a count so adding a second identical
violation on a new line still trips the gate.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # "G001"
    path: str  # repo-relative posix path
    line: int  # 1-based
    col: int  # 0-based
    message: str
    snippet: str  # stripped source line (fingerprint component)
    # Last line of the flagged node (multi-line statements): waivers
    # anywhere in [line, end_line] bind to this finding.  NOT part of
    # the fingerprint — reformatting must not un-freeze a baseline.
    end_line: int = 0

    def fingerprint(self) -> str:
        return f"{self.rule}|{self.path}|{self.snippet}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format_text(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: {self.rule} {self.message}"


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    new_findings: List[Finding]  # after baseline subtraction
    parse_errors: List[Finding]  # syntax errors reported as G000
    # Machine-readable contract inventory (fetch sites, failpoint
    # sites, env knobs, waiver census) built from the same parsed
    # files — tools/ci.sh drift-checks it against the committed copy.
    inventory: Optional[dict] = None
    # The package context the run was built from (registry
    # regeneration re-walks it; never serialized).
    pkg: Optional["PackageContext"] = None

    @property
    def failed(self) -> bool:
        return bool(self.new_findings) or bool(self.parse_errors)


def _parse_waiver_segments(comment: str) -> List[Tuple[Set[str], str]]:
    """``# lint: waive G001 -- why; lint: fetch-site -- why2`` ->
    [({"G001"}, "why"), ({"fetch-site"}, "why2")].

    Every ``lint:`` segment in the comment is parsed (stacked waivers on
    one line must ALL match — pinned by tests).  The justification
    separator accepts ``--`` and the unicode dashes people actually type
    (– —); only well-formed tokens (rule ids / kebab-case aliases)
    count, so a missing separator can never let a justification word
    accidentally waive another rule."""
    out: List[Tuple[Set[str], str]] = []
    for segment in re.split(r"lint:", comment)[1:]:
        segment = segment.split("#")[0]
        parts = re.split(r"--|[–—]", segment, maxsplit=1)
        body = parts[0]
        justification = parts[1].strip().rstrip(";").strip() if (
            len(parts) > 1
        ) else ""
        # A stacked comment separates segments with ';' — keep the
        # leading segment's tokens clean of the next segment's prose.
        body = body.split(";")[0]
        tokens = {
            t
            for t in re.split(r"[,\s]+", body.strip())
            if re.fullmatch(r"[A-Za-z][A-Za-z0-9_-]*", t)
        }
        tokens.discard("waive")
        if tokens:
            out.append((tokens, justification))
    return out


def _parse_waiver_tokens(comment: str) -> Set[str]:
    """Union of every stacked segment's tokens (the waiver matcher)."""
    tokens: Set[str] = set()
    for seg_tokens, _just in _parse_waiver_segments(comment):
        tokens |= seg_tokens
    return tokens


class FileContext:
    """One parsed file: AST + comment map + waiver map + module constants.

    ``fragment`` is an optional per-file cache entry (tools/lint/
    cache.py, keyed by mtime+size): when given, the tokenize comment
    scan and the module-constant walks are skipped and the cached
    facts installed instead — bit-identical results, pinned by tests.
    """

    def __init__(
        self, path: str, source: str, fragment: Optional[dict] = None
    ):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[Finding] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.parse_error = Finding(
                rule="G000",
                path=self.path,
                line=e.lineno or 1,
                col=(e.offset or 1) - 1,
                message=f"syntax error: {e.msg}",
                snippet=self._line(e.lineno or 1),
            )
        self.comments: Dict[int, str] = {}
        self.waivers: Dict[int, Set[str]] = {}
        # line -> [(tokens, justification)] per stacked segment (the
        # inventory's waiver census reads the justifications).
        self.waiver_details: Dict[int, List[Tuple[Set[str], str]]] = {}
        self.str_consts: Dict[str, str] = {}
        self.int_consts: Dict[str, int] = {}
        use_fragment = fragment is not None and self.tree is not None
        if use_fragment:
            from tools.lint import cache as _cache

            _cache.apply_fragment(self, fragment)
        else:
            self._scan_comments()
        # Node-type index: ONE ast.walk per file, shared by every rule
        # and census pass (the profiled v2 wall was dominated by each
        # rule re-walking every whole-file tree — lint wall time is
        # CI-budgeted at 15 s).  Subtree walks (function bodies) still
        # use ast.walk; only whole-tree scans go through the index.
        self._by_type: Dict[type, List[ast.AST]] = {}
        self._enclosing_fn: Optional[Dict[int, ast.AST]] = None
        # Decorated def/class line -> extra lines whose waivers attach
        # to it (each decorator line + the line above the first one).
        self._decorator_alt: Dict[int, List[int]] = {}
        self._functions_bfs: List[ast.AST] = []
        if self.tree is not None:
            for node in ast.walk(self.tree):
                self._by_type.setdefault(type(node), []).append(node)
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    self._functions_bfs.append(node)
            if not use_fragment:
                self._collect_consts()
            self._collect_decorator_spans()

    def nodes(self, *types: type) -> List[ast.AST]:
        """Every node of the given AST type(s), in one-walk BFS order
        per type (deterministic; use for whole-file scans)."""
        if len(types) == 1:
            return self._by_type.get(types[0], [])
        out: List[ast.AST] = []
        for t in types:
            out.extend(self._by_type.get(t, ()))
        return out

    def enclosing_functions(self) -> Dict[int, ast.AST]:
        """``id(node) -> innermost enclosing FunctionDef`` for every
        node under a function (built lazily once per file and shared:
        G012 and the v3 collective rules all need it).  Functions are
        visited in BFS order, so the deepest function's assignment
        wins."""
        if self._enclosing_fn is None:
            enclosing: Dict[int, ast.AST] = {}
            for fn in self._functions_bfs:
                for sub in ast.walk(fn):
                    enclosing[id(sub)] = fn
            self._enclosing_fn = enclosing
        return self._enclosing_fn

    def _line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def _scan_comments(self) -> None:
        try:
            for tok in tokenize.generate_tokens(
                io.StringIO(self.source).readline
            ):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
                    segments = _parse_waiver_segments(tok.string)
                    if segments:
                        self.waiver_details[tok.start[0]] = segments
                        self.waivers[tok.start[0]] = set().union(
                            *(t for t, _ in segments)
                        )
        except (tokenize.TokenError, IndentationError):
            pass  # parse_error already carries the report

    def _collect_consts(self) -> None:
        for node in ast.iter_child_nodes(self.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if isinstance(node.value, ast.Constant):
                if isinstance(node.value.value, str):
                    self.str_consts[tgt.id] = node.value.value
                elif isinstance(node.value.value, int) and not isinstance(
                    node.value.value, bool
                ):
                    self.int_consts[tgt.id] = node.value.value

    def _collect_decorator_spans(self) -> None:
        for node in self.nodes(
            ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef
        ):
            if not node.decorator_list:
                continue
            first = min(d.lineno for d in node.decorator_list)
            # Findings on a decorated def anchor at the `def` line; a
            # waiver written above the decorator stack (or on any
            # decorator line) must attach there too.
            self._decorator_alt[node.lineno] = [first - 1] + sorted(
                d.lineno for d in node.decorator_list
            )

    def is_waived(
        self,
        rule_id: str,
        aliases: Sequence[str],
        line: int,
        end_line: int = 0,
    ) -> bool:
        candidates = set(range(line - 1, max(end_line, line) + 1))
        candidates.update(self._decorator_alt.get(line, ()))
        for ln in candidates:
            toks = self.waivers.get(ln)
            if toks and (rule_id in toks or any(a in toks for a in aliases)):
                return True
        return False


class PackageContext:
    """Cross-file facts rules may consult (built in a first pass)."""

    def __init__(
        self,
        files: Sequence[FileContext],
        env_registry: Optional[dict] = None,
    ):
        from tools.lint.graph import PackageGraph

        self.files = files
        self.by_path: Dict[str, FileContext] = {f.path: f for f in files}
        # The v2 symbol table / call graph (tools/lint/graph.py): rules
        # resolve renamed imports, cross-file constants, and callees
        # through it.
        self.graph = PackageGraph(files)
        # Committed FA_* knob registry (tools/lint/env_registry.json);
        # None when linting sources with no registry to check against.
        self.env_registry = env_registry
        # NAME -> str value, package-wide (for `from ... import AXIS`).
        self.str_consts: Dict[str, str] = {}
        for f in files:
            self.str_consts.update(f.str_consts)
        self.declared_axes: Set[str] = set()
        for f in files:
            if f.tree is not None:
                self._collect_axes(f)

    # Axis-declaration sources.  ``P``/``PartitionSpec`` literals count
    # as declarations (G002 satellite): a spec names the mesh axes it
    # shards over, and this codebase writes specs next to the shard_map
    # they feed — a typo'd spec axis fails the same trace-time way and
    # is caught by the same census.
    _MESH_CTORS = ("Mesh", "make_mesh", "AbstractMesh", "P", "PartitionSpec")
    _SHARD_CALLS = ("shard_map", "smap", "pmap")

    def _collect_axes(self, ctx: FileContext) -> None:
        """Mesh axis declarations: string literals (or resolvable names)
        anywhere in the arguments of ``Mesh(...)`` / ``make_mesh(...)`` /
        ``AbstractMesh(...)`` / ``P(...)`` / ``PartitionSpec(...)``
        calls, plus the ``axis_names=`` / ``axis_name=`` keywords of
        ``shard_map(...)``-style calls (the keyword spelling ROADMAP
        queued for G002)."""
        for node in ctx.nodes(ast.Call):
            t = terminal_name(node.func)
            if t in self._MESH_CTORS:
                exprs = list(node.args) + [
                    kw.value for kw in node.keywords
                ]
            elif t in self._SHARD_CALLS:
                exprs = [
                    kw.value
                    for kw in node.keywords
                    if kw.arg in ("axis_names", "axis_name")
                ]
            else:
                continue
            for arg in exprs:
                for sub in ast.walk(arg):
                    s = resolve_str(sub, ctx, self)
                    if s is not None:
                        self.declared_axes.add(s)


def terminal_name(node: ast.AST) -> Optional[str]:
    """`jax.experimental.shard_map.shard_map` -> "shard_map"; Name -> id."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """Full dotted path when the expression is a pure attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_str(
    node: ast.AST, ctx: FileContext, pkg: Optional["PackageContext"] = None
) -> Optional[str]:
    """Constant str, or a Name resolvable to a module-level /
    package-level string constant — including, via the v2 graph, a
    renamed cross-file import (``from pkg.meshdef import AXIS as A``)
    or a dotted module reference (``meshdef.AXIS``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        if node.id in ctx.str_consts:
            return ctx.str_consts[node.id]
        if pkg is not None and node.id in pkg.str_consts:
            return pkg.str_consts[node.id]
    if pkg is not None and isinstance(node, (ast.Name, ast.Attribute)):
        return pkg.graph.resolve_str_const(ctx, node)
    return None


def resolve_label(
    node: ast.AST, ctx: FileContext, pkg: Optional["PackageContext"] = None
) -> Optional[str]:
    """Compile-time string resolution for site labels (the G013 v3
    closure): literals, module/package/cross-file constants
    (:func:`resolve_str`), f-strings, ``+``/``%`` concatenation, and
    ``.format(...)`` — each over resolvable parts only.  ``None`` when
    any part is genuinely dynamic (a loop variable, a parameter): such
    labels are census blind spots and G013 flags them for a waiver."""
    s = resolve_str(node, ctx, pkg)
    if s is not None:
        return s
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif isinstance(v, ast.FormattedValue):
                sub = resolve_label(v.value, ctx, pkg)
                if sub is None:
                    return None
                parts.append(sub)
            else:
                return None
        return "".join(parts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = resolve_label(node.left, ctx, pkg)
        right = resolve_label(node.right, ctx, pkg)
        if left is not None and right is not None:
            return left + right
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
        fmt = resolve_label(node.left, ctx, pkg)
        if fmt is None:
            return None
        rhs = (
            list(node.right.elts)
            if isinstance(node.right, ast.Tuple)
            else [node.right]
        )
        vals = [resolve_label(r, ctx, pkg) for r in rhs]
        if any(v is None for v in vals):
            return None
        try:
            return fmt % tuple(vals)
        except (TypeError, ValueError):
            return None
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "format"
        and not node.keywords
    ):
        fmt = resolve_label(node.func.value, ctx, pkg)
        if fmt is None:
            return None
        vals = [resolve_label(a, ctx, pkg) for a in node.args]
        if any(v is None for v in vals):
            return None
        try:
            return fmt.format(*vals)
        except (IndexError, KeyError, ValueError):
            return None
    return None


def resolve_int(
    node: ast.AST, ctx: FileContext, pkg: Optional["PackageContext"] = None
) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name) and node.id in ctx.int_consts:
        return ctx.int_consts[node.id]
    if pkg is not None and isinstance(node, (ast.Name, ast.Attribute)):
        return pkg.graph.resolve_int_const(ctx, node)
    return None


# ---------------------------------------------------------------------------
# Runner


def iter_py_files(paths: Iterable[str], root: str = ".") -> List[str]:
    out: List[str] = []
    for p in paths:
        full = os.path.join(root, p) if not os.path.isabs(p) else p
        if os.path.isfile(full) and full.endswith(".py"):
            out.append(full)
        elif os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = sorted(
                    d
                    for d in dirnames
                    if not d.startswith(".") and d != "__pycache__"
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
    return out


def _run_rules(
    files: Sequence[FileContext],
    rules: Sequence,
    env_registry: Optional[dict] = None,
) -> Tuple[List[Finding], List[Finding], "PackageContext"]:
    pkg = PackageContext(files, env_registry=env_registry)
    findings: List[Finding] = []
    parse_errors = [f.parse_error for f in files if f.parse_error is not None]
    for ctx in files:
        if ctx.tree is None:
            continue
        for rule in rules:
            for finding in rule.check(ctx, pkg):
                if not ctx.is_waived(
                    rule.id, rule.aliases, finding.line, finding.end_line
                ):
                    findings.append(finding)
    # Package-wide rules (the v2 census rules): findings may land in any
    # file; waivers still apply through the owning file's context.
    for rule in rules:
        for finding in rule.check_package(pkg):
            ctx = pkg.by_path.get(finding.path)
            if ctx is None or not ctx.is_waived(
                rule.id, rule.aliases, finding.line, finding.end_line
            ):
                findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, parse_errors, pkg


def lint_sources(
    sources: Sequence[Tuple[str, str]],
    rules: Optional[Sequence] = None,
    env_registry: Optional[dict] = None,
) -> LintResult:
    """In-memory entry point (what tests/test_lint.py drives):
    ``sources`` is [(relpath, source_text), ...]."""
    if rules is None:
        from tools.lint.rules import ALL_RULES as rules  # noqa: N811
    files = [FileContext(p, s) for p, s in sources]
    findings, parse_errors, pkg = _run_rules(
        files, rules, env_registry=env_registry
    )
    return LintResult(
        findings, list(findings), parse_errors, build_inventory(pkg), pkg
    )


ENV_REGISTRY_PATH = os.path.join("tools", "lint", "env_registry.json")
INVENTORY_PATH = os.path.join("tools", "lint", "inventory.json")


def load_env_registry(root: str = ".") -> Optional[dict]:
    """The committed FA_* knob registry, or None when the tree being
    linted does not carry one (fixture packages)."""
    path = os.path.join(root, ENV_REGISTRY_PATH)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return None
    if not isinstance(data, dict) or "vars" not in data:
        raise ValueError(f"{path}: not a graftlint env registry file")
    return data


def lint_paths(
    paths: Sequence[str],
    root: str = ".",
    baseline: Optional[dict] = None,
    rules: Optional[Sequence] = None,
    env_registry: Optional[dict] = None,
    use_cache: bool = True,
) -> LintResult:
    from tools.lint import cache as _cache

    if rules is None:
        from tools.lint.rules import ALL_RULES as rules  # noqa: N811
    if env_registry is None:
        env_registry = load_env_registry(root)
    cached = _cache.load(root) if use_cache else {}
    fresh: Dict[str, dict] = {}
    files = []
    for fp in iter_py_files(paths, root):
        rel = os.path.relpath(fp, root)
        rel_posix = rel.replace(os.sep, "/")
        try:
            with open(fp, "r", encoding="utf-8") as fh:
                source = fh.read()
        except (OSError, UnicodeDecodeError) as e:
            files.append(FileContext(rel, ""))
            files[-1].parse_error = Finding(
                "G000", rel_posix, 1, 0, f"unreadable: {e}", ""
            )
            continue
        fragment = _cache.lookup(cached, rel_posix, fp)
        ctx = FileContext(rel, source, fragment=fragment)
        files.append(ctx)
        if use_cache:
            fragment = fragment or _cache.to_fragment(ctx, fp)
            if fragment is not None:
                fresh[rel_posix] = fragment
    if use_cache:
        # Keep entries for files this (possibly subset-path) run never
        # visited: a targeted `tools.lint some/file.py` must not evict
        # the full tree's warm cache.  Stale entries self-invalidate
        # at lookup (mtime+size) and die with the next lint-source
        # fingerprint change.
        for rel_posix, entry in cached.items():
            fresh.setdefault(rel_posix, entry)
        _cache.save(root, fresh)
    findings, parse_errors, pkg = _run_rules(
        files, rules, env_registry=env_registry
    )
    new = subtract_baseline(findings, baseline or {})
    return LintResult(findings, new, parse_errors, build_inventory(pkg), pkg)


# ---------------------------------------------------------------------------
# Baseline


def load_baseline(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or "fingerprints" not in data:
        raise ValueError(f"{path}: not a graftlint baseline file")
    return data


def make_baseline(findings: Sequence[Finding]) -> dict:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.fingerprint()] = counts.get(f.fingerprint(), 0) + 1
    return {
        "version": 1,
        "comment": (
            "Findings frozen at baseline time; the CLI fails only on "
            "findings beyond these counts.  Regenerate with "
            "`python -m tools.lint ... --write-baseline`."
        ),
        "fingerprints": dict(sorted(counts.items())),
    }


# ---------------------------------------------------------------------------
# Contract inventory (v2): the machine-readable census of the repo's
# prose-documented preconditions — audited fetch sites, failpoint sites,
# FA_* env knobs, and the waiver audit trail.  tools/ci.sh drift-checks
# the committed tools/lint/inventory.json against a fresh build, so
# inventory churn must ride the PR that causes it.

_RETRY_FETCH_FQS = (
    "fastapriori_tpu.reliability.retry.fetch",
    "fastapriori_tpu.reliability.retry.fetch_async",
)
_FAILPOINT_FIRE_FQ = "fastapriori_tpu.reliability.failpoints.fire"
_ENV_VAR_RE = re.compile(r"FA_[A-Z0-9_]+")


def is_test_path(path: str) -> bool:
    parts = path.replace(os.sep, "/").split("/")
    return any(p in ("tests", "tests_tpu") for p in parts)


def _param_label_values(
    ctx: FileContext,
    pkg: "PackageContext",
    fn: ast.AST,
    param: str,
) -> List[str]:
    """Every compile-time value flowing into ``fn``'s ``param`` across
    the package: the parameter's literal default plus the resolved
    argument at every graph-resolvable call site (the
    ``gather_level_counts_start(site=...)`` pattern — the helper's ONE
    fetch call fans out to one censused label per caller)."""
    values: List[str] = []
    args = fn.args
    params = [a.arg for a in list(args.posonlyargs) + list(args.args)]
    if param in params:
        idx = params.index(param)
        d_idx = idx - (len(params) - len(args.defaults))
        if 0 <= d_idx < len(args.defaults):
            v = resolve_label(args.defaults[d_idx], ctx, pkg)
            if v is not None:
                values.append(v)
    else:
        # Keyword-only label parameter: its default lives in
        # kw_defaults, and call sites can only pass it by keyword.
        for kwarg, default in zip(args.kwonlyargs, args.kw_defaults):
            if kwarg.arg == param and default is not None:
                v = resolve_label(default, ctx, pkg)
                if v is not None:
                    values.append(v)
    # Bound-method calls drop the explicit self/cls argument.
    call_idx = params.index(param) if param in params else -1
    if call_idx >= 0 and params and params[0] in ("self", "cls"):
        call_idx -= 1
    for other in pkg.files:
        if other.tree is None:
            continue
        for call in other.nodes(ast.Call):
            hit = pkg.graph.resolve_call(other, call)
            if hit is None or hit[1] is not fn:
                continue
            expr = None
            for kw in call.keywords:
                if kw.arg == param:
                    expr = kw.value
            if expr is None and 0 <= call_idx < len(call.args):
                expr = call.args[call_idx]
            if expr is None:
                continue
            v = resolve_label(expr, other, pkg)
            if v is not None:
                values.append(v)
    return values


def fetch_label_sites(ctx: FileContext, pkg: "PackageContext"):
    """``(resolved, unresolved)`` for this file's audited-fetch-helper
    calls (resolved to the reliability module through the graph — a
    local ``fetch()`` of some cache API does not count; a renamed
    import still does).  ``resolved`` is ``[(label, call-node)]``:
    labels are compile-time resolved (:func:`resolve_label` — literals,
    constants, f-strings/``%``/``.format`` over resolvables), and a
    label that is a PARAMETER of the enclosing helper censuses once per
    compile-time value flowing into it package-wide (default +
    resolvable call-site arguments).  ``unresolved`` is the call nodes
    whose label stayed dynamic — census blind spots G013 flags."""
    resolved = []
    unresolved = []
    enclosing = None
    for node in ctx.nodes(ast.Call):
        fq = pkg.graph.resolve_expr(ctx, node.func)
        if fq not in _RETRY_FETCH_FQS:
            continue
        exprs = list(node.args) + [
            kw.value for kw in node.keywords if kw.arg == "site"
        ]
        label = None
        for a in exprs:
            label = resolve_label(a, ctx, pkg)
            if label is not None:
                break
        if label is not None:
            resolved.append((label, node))
            continue
        # Param-flow: a Name argument that is a parameter of the
        # enclosing function censuses per inflowing value.
        if enclosing is None:
            enclosing = ctx.enclosing_functions()
        fn = enclosing.get(id(node))
        values: List[str] = []
        if fn is not None:
            fn_params = {
                a.arg
                for a in list(fn.args.posonlyargs)
                + list(fn.args.args)
                + list(fn.args.kwonlyargs)
            }
            for a in exprs:
                if isinstance(a, ast.Name) and a.id in fn_params:
                    values = _param_label_values(ctx, pkg, fn, a.id)
                    if values:
                        break
        if values:
            for v in sorted(set(values)):
                resolved.append((v, node))
        else:
            unresolved.append(node)
    return resolved, unresolved


def failpoint_fire_sites(ctx: FileContext, pkg: "PackageContext"):
    """``(resolved, unresolved)`` for ``failpoints.fire(...)`` sites.
    Labels resolve through the same compile-time machinery as fetch
    labels (v3 closed the G013 residue: constants, f-strings,
    ``"write." + name``-style concatenation over resolvables).  Sites
    that stay dynamic land in ``unresolved`` and G013 flags them — the
    three real ones (the retry helper's central instrumentation, the
    atomic writer's per-artifact family, the per-level family) carry
    waivers naming their site families."""
    resolved = []
    unresolved = []
    for node in ctx.nodes(ast.Call):
        fq = pkg.graph.resolve_expr(ctx, node.func)
        if fq != _FAILPOINT_FIRE_FQ:
            d = dotted_name(node.func)
            if d is None or not d.endswith("failpoints.fire"):
                continue
        if not node.args:
            continue
        label = resolve_label(node.args[0], ctx, pkg)
        if label is not None:
            resolved.append((label, node))
        else:
            unresolved.append(node)
    return resolved, unresolved


def env_read_sites(ctx: FileContext):
    """``(name, node)`` for every FA_* environment READ: ``os.environ
    .get``/``os.getenv``/``os.environ[...]`` (loads only — tests that
    SET knobs are not reads)."""
    out = []
    for node in ctx.nodes(ast.Call, ast.Subscript):
        name_node = None
        if isinstance(node, ast.Call):
            d = dotted_name(node.func) or ""
            if d.endswith("environ.get") or d in ("os.getenv", "getenv"):
                if node.args:
                    name_node = node.args[0]
        elif isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Load
        ):
            d = dotted_name(node.value) or ""
            if d.endswith("environ"):
                name_node = node.slice
                if isinstance(name_node, getattr(ast, "Index", ())):
                    name_node = name_node.value  # py<3.9 AST shape
        if (
            name_node is not None
            and isinstance(name_node, ast.Constant)
            and isinstance(name_node.value, str)
            and name_node.value.startswith("FA_")
        ):
            out.append((name_node.value, node))
    return out


def str_constant_paths(pkg: "PackageContext") -> Dict[str, Set[str]]:
    """Every string literal in the package -> paths holding it (built
    once per run; the census rules and the registry scan share it)."""
    cached = getattr(pkg, "_str_constant_paths", None)
    if cached is not None:
        return cached
    out: Dict[str, Set[str]] = {}
    for ctx in pkg.files:
        if ctx.tree is None:
            continue
        for node in ctx.nodes(ast.Constant):
            if isinstance(node.value, str):
                out.setdefault(node.value, set()).add(ctx.path)
    pkg._str_constant_paths = out
    return out


def env_var_references(pkg: "PackageContext") -> Dict[str, Set[str]]:
    """FA_* name -> paths holding a whole-string literal reference —
    the registry-completeness universe (covers knobs read by native
    code but exercised from tests, e.g. FA_NO_SIMD)."""
    return {
        value: paths
        for value, paths in str_constant_paths(pkg).items()
        if _ENV_VAR_RE.fullmatch(value)
    }


def site_census(pkg: "PackageContext"):
    """``(fetch_sites, fire_sites, env_reads, unresolved)`` over every
    NON-TEST file — the first three as ``[(key, ctx, node)]``,
    ``unresolved`` as ``[(kind, ctx, node)]`` for fetch/fire sites
    whose label stayed dynamic after the compile-time resolution (G013
    flags those: a label the census cannot prove is a blind spot).
    Built once per run and shared by G013 and the inventory builder."""
    cached = getattr(pkg, "_site_census", None)
    if cached is not None:
        return cached
    fetches, fires, envs, unresolved = [], [], [], []
    for ctx in pkg.files:
        if ctx.tree is None or is_test_path(ctx.path):
            continue
        resolved, blind = fetch_label_sites(ctx, pkg)
        for label, node in resolved:
            fetches.append((label, ctx, node))
        for node in blind:
            unresolved.append(("fetch", ctx, node))
        resolved, blind = failpoint_fire_sites(ctx, pkg)
        for site, node in resolved:
            fires.append((site, ctx, node))
        for node in blind:
            unresolved.append(("failpoint", ctx, node))
        for name, node in env_read_sites(ctx):
            envs.append((name, ctx, node))
    pkg._site_census = (fetches, fires, envs, unresolved)
    return pkg._site_census


def span_declarations(pkg: "PackageContext"):
    """``[(literal, ctx, node)]`` for every string constant inside an
    assignment to ``FETCH_SITE_SPANS`` in a NON-TEST file — the span
    tracer's statically-checkable claim of which audited fetch sites
    receive span scopes (fastapriori_tpu/obs/trace.py).  G014 checks
    the claim against the fetch census both ways; the inventory ships
    it as the ``span_sites`` census.  Cached per run."""
    cached = getattr(pkg, "_span_declarations", None)
    if cached is not None:
        return cached
    out = []
    for ctx in pkg.files:
        if ctx.tree is None or is_test_path(ctx.path):
            continue
        for node in ctx.nodes(ast.Assign, ast.AnnAssign):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            if not any(
                isinstance(t, ast.Name) and t.id == "FETCH_SITE_SPANS"
                for t in targets
            ):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str
                ):
                    out.append((sub.value, ctx, sub))
    pkg._span_declarations = out
    return out


def _counted(entries):
    """[(key-dict)] -> sorted unique entries with a ``count`` field."""
    counts: Dict[Tuple, int] = {}
    for e in entries:
        key = tuple(sorted(e.items()))
        counts[key] = counts.get(key, 0) + 1
    out = []
    for key, n in sorted(counts.items()):
        d = dict(key)
        d["count"] = n
        out.append(d)
    return out


def build_inventory(pkg: "PackageContext") -> dict:
    """Deterministic contract inventory over the linted package (test
    files are excluded from the site censuses — they exercise sites,
    they do not define them — but included in the waiver census)."""
    fetch_census, fire_census, env_census, _unresolved = site_census(pkg)
    fetches = [{"label": l, "path": c.path} for l, c, _n in fetch_census]
    fires = [{"site": s, "path": c.path} for s, c, _n in fire_census]
    envs = [{"name": n, "path": c.path} for n, c, _n in env_census]
    waivers = []
    for ctx in pkg.files:
        if ctx.tree is None:
            continue
        for _line, segments in sorted(ctx.waiver_details.items()):
            for tokens, justification in segments:
                waivers.append(
                    {
                        "path": ctx.path,
                        "tokens": ",".join(sorted(tokens)),
                        "justification": justification,
                    }
                )
    spans = [
        {"label": v, "path": c.path}
        for v, c, _n in span_declarations(pkg)
    ]
    # The ISSUE 18 kernel census: every ``pallas_call`` site in
    # non-test code, by enclosing function — the inventory row that
    # makes a new device kernel a reviewed, drift-checked event (a
    # kernel added without regenerating the inventory fails
    # --check-inventory in CI).
    kernels = []
    for ctx in pkg.files:
        if ctx.tree is None or is_test_path(ctx.path):
            continue
        func_stack = ["<module>"]

        def _walk(node):
            is_fn = isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            )
            if is_fn:
                func_stack.append(node.name)
            if isinstance(node, ast.Call):
                fn = node.func
                name = (
                    fn.attr
                    if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None
                )
                if name == "pallas_call":
                    kernels.append(
                        {"path": ctx.path, "function": func_stack[-1]}
                    )
            for child in ast.iter_child_nodes(node):
                _walk(child)
            if is_fn:
                func_stack.pop()

        _walk(ctx.tree)
    # The v3 collective census (tools/lint/collective.py): every
    # collective-issuing call site with its mesh axis, issuing engine
    # path, and enclosing branch conditions — the artifact G015-G017
    # prove their guard properties against, drift-checked like the
    # fetch/failpoint censuses.
    from tools.lint import collective as coll

    collectives = [s.to_entry() for s in coll.census(pkg)]
    # The v4 protocol censuses (tools/lint/protocol.py): every raise
    # site, ledger-event emission, and CHAINS walk — the artifacts
    # G018-G020 prove the error-classification / cascade / fence
    # contracts against, drift-checked like everything above.
    from tools.lint import protocol as proto

    # The v5 concurrency censuses (tools/lint/concurrency.py): every
    # thread spawn, blocking primitive (with its boundedness class),
    # lock acquisition, ring/queue hand-off, shutdown-sentinel
    # declaration/delivery/check, and quorum/router marker-path
    # construction — the artifacts G021-G024 prove the liveness /
    # race / swap-barrier / epoch-namespace contracts against,
    # drift-checked like everything above.
    from tools.lint import concurrency as conc

    return {
        "version": 1,
        "comment": (
            "Generated by `python -m tools.lint ... --write-inventory`; "
            "drift-checked by tools/ci.sh.  Regenerate in the PR that "
            "changes any censused site."
        ),
        "fetch_sites": _counted(fetches),
        "failpoint_sites": _counted(fires),
        "span_sites": _counted(spans),
        "kernel_sites": _counted(kernels),
        "env_reads": _counted(envs),
        "collective_sites": _counted(collectives),
        "raise_sites": _counted(proto.raise_census(pkg)),
        "ledger_events": _counted(proto.ledger_census(pkg)),
        "chain_walks": _counted(proto.chain_walk_census(pkg)),
        "thread_spawns": _counted(conc.spawn_census(pkg)),
        "blocking_sites": _counted(conc.blocking_census(pkg)),
        "lock_sites": _counted(conc.lock_census(pkg)),
        "handoff_sites": _counted(conc.handoff_census(pkg)),
        "sentinel_sites": _counted(conc.sentinel_census(pkg)),
        "marker_paths": _counted(conc.marker_census(pkg)),
        "waivers": _counted(waivers),
    }


def regenerate_env_registry(
    pkg: "PackageContext", existing: Optional[dict]
) -> dict:
    """Rebuild tools/lint/env_registry.json deterministically from the
    parsed package: the variable set and reader paths come from the
    scan; descriptions are carried over from the committed registry
    (new knobs get an empty description for a human to fill in — G012
    keeps unknown reads failing until the entry exists)."""
    old_vars = (existing or {}).get("vars", {})
    refs = env_var_references(pkg)
    # Test files reference knobs two ways that must not be conflated: a
    # test exercising a REAL knob keeps its (possibly native-read, e.g.
    # FA_NO_SIMD) registry entry alive, but a lint FIXTURE knob living
    # only in test sources must never enter the registry.  So test-only
    # references RETAIN existing entries and never ADD new ones.
    nontest_names: Set[str] = set()
    for name, paths in refs.items():
        if any(not is_test_path(p) for p in paths):
            nontest_names.add(name)
    names = nontest_names | (set(old_vars) & set(refs))
    readers: Dict[str, Set[str]] = {}
    for name, ctx, _node in site_census(pkg)[2]:  # env reads
        readers.setdefault(name, set()).add(ctx.path)
    # Knobs read through the strict helpers (utils/env.py) have no
    # literal os.environ read at the call site — the literal name
    # handed to ANY call in non-test code marks the reader.
    for ctx in pkg.files:
        if ctx.tree is None or is_test_path(ctx.path):
            continue
        for node in ctx.nodes(ast.Call):
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(a, ast.Constant) and isinstance(
                    a.value, str
                ) and _ENV_VAR_RE.fullmatch(a.value):
                    readers.setdefault(a.value, set()).add(ctx.path)
    out_vars = {}
    for name in sorted(names):
        entry = {
            "description": old_vars.get(name, {}).get("description", ""),
            "readers": sorted(readers.get(name, ())),
        }
        out_vars[name] = entry
    return {
        "version": 1,
        "comment": (
            "FA_* knob registry: the variable set and reader paths are "
            "generated (`--write-inventory`); descriptions are "
            "hand-written and preserved across regenerations.  G012 "
            "fails reads of unregistered knobs and flags stale entries."
        ),
        "vars": out_vars,
    }


def render_env_table(registry: dict) -> str:
    """The README's FA_* knob table, rendered from the checked registry
    so the docs cannot drift from the artifact."""
    lines = [
        "| knob | read at | purpose |",
        "|------|---------|---------|",
    ]
    for name, entry in sorted(registry.get("vars", {}).items()):
        readers = ", ".join(f"`{p}`" for p in entry.get("readers", []))
        if not readers:
            readers = "— (native code / tests only)"
        desc = entry.get("description", "") or "*(undocumented)*"
        lines.append(f"| `{name}` | {readers} | {desc} |")
    return "\n".join(lines) + "\n"


def subtract_baseline(
    findings: Sequence[Finding], baseline: dict
) -> List[Finding]:
    budget = dict(baseline.get("fingerprints", {}))
    new: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            new.append(f)
    return new
