"""graftlint rule engine: file contexts, waivers, baseline, and the runner.

The engine is deliberately dumb about JAX semantics — each rule
(tools/lint/rules.py) encodes ONE contract of this codebase and gets a
parsed view of every file plus a package-wide symbol table (declared mesh
axis names, module-level string/int constants).  Everything here is
stdlib-only; the linter must run on machines with no JAX installed.

Waiver syntax (the audit trail the rules exist to force):

    x = np.asarray(counts_dev)  # lint: fetch-site -- end-of-mine fetch
    except Exception:  # lint: waive G006 -- optional-dep probe

A ``# lint:`` comment on the flagged line or the line directly above it
waives matching rules on that line.  Tokens are either a rule id
(``G001``) after the word ``waive``, or a rule's named alias
(``fetch-site``); anything after ``--`` is the human justification and is
ignored by the matcher (but reviewers should insist on it).

Baselines freeze pre-existing findings so the CLI only fails on NEW ones:
a finding's fingerprint is ``rule|path|stripped-source-line`` (line
numbers excluded on purpose — unrelated edits must not un-freeze a
baselined finding), stored with a count so adding a second identical
violation on a new line still trips the gate.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_WAIVER_RE = re.compile(r"lint:\s*([^#]*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # "G001"
    path: str  # repo-relative posix path
    line: int  # 1-based
    col: int  # 0-based
    message: str
    snippet: str  # stripped source line (fingerprint component)

    def fingerprint(self) -> str:
        return f"{self.rule}|{self.path}|{self.snippet}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format_text(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: {self.rule} {self.message}"


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    new_findings: List[Finding]  # after baseline subtraction
    parse_errors: List[Finding]  # syntax errors reported as G000

    @property
    def failed(self) -> bool:
        return bool(self.new_findings) or bool(self.parse_errors)


def _parse_waiver_tokens(comment: str) -> Set[str]:
    """``# lint: waive G001, G006 -- why`` -> {"G001", "G006"}.

    The justification separator accepts ``--`` and the unicode dashes
    people actually type (– —); and only well-formed tokens (rule ids /
    kebab-case aliases) count, so a missing separator can never let a
    justification word accidentally waive another rule."""
    m = _WAIVER_RE.search(comment)
    if not m:
        return set()
    body = re.split(r"--|[–—]", m.group(1))[0]
    tokens = {
        t
        for t in re.split(r"[,\s]+", body.strip())
        if re.fullmatch(r"[A-Za-z][A-Za-z0-9_-]*", t)
    }
    tokens.discard("waive")
    return tokens


class FileContext:
    """One parsed file: AST + comment map + waiver map + module constants."""

    def __init__(self, path: str, source: str):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[Finding] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.parse_error = Finding(
                rule="G000",
                path=self.path,
                line=e.lineno or 1,
                col=(e.offset or 1) - 1,
                message=f"syntax error: {e.msg}",
                snippet=self._line(e.lineno or 1),
            )
        self.comments: Dict[int, str] = {}
        self.waivers: Dict[int, Set[str]] = {}
        self._scan_comments()
        self.str_consts: Dict[str, str] = {}
        self.int_consts: Dict[str, int] = {}
        if self.tree is not None:
            self._collect_consts()

    def _line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def _scan_comments(self) -> None:
        try:
            for tok in tokenize.generate_tokens(
                io.StringIO(self.source).readline
            ):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
                    waived = _parse_waiver_tokens(tok.string)
                    if waived:
                        self.waivers[tok.start[0]] = waived
        except (tokenize.TokenError, IndentationError):
            pass  # parse_error already carries the report

    def _collect_consts(self) -> None:
        for node in ast.iter_child_nodes(self.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if isinstance(node.value, ast.Constant):
                if isinstance(node.value.value, str):
                    self.str_consts[tgt.id] = node.value.value
                elif isinstance(node.value.value, int) and not isinstance(
                    node.value.value, bool
                ):
                    self.int_consts[tgt.id] = node.value.value

    def is_waived(self, rule_id: str, aliases: Sequence[str], line: int) -> bool:
        for ln in (line, line - 1):
            toks = self.waivers.get(ln)
            if toks and (rule_id in toks or any(a in toks for a in aliases)):
                return True
        return False


class PackageContext:
    """Cross-file facts rules may consult (built in a first pass)."""

    def __init__(self, files: Sequence[FileContext]):
        self.files = files
        # NAME -> str value, package-wide (for `from ... import AXIS`).
        self.str_consts: Dict[str, str] = {}
        for f in files:
            self.str_consts.update(f.str_consts)
        self.declared_axes: Set[str] = set()
        for f in files:
            if f.tree is not None:
                self._collect_axes(f)

    def _collect_axes(self, ctx: FileContext) -> None:
        """Mesh axis declarations: string literals (or resolvable names)
        anywhere in the arguments of ``Mesh(...)`` / ``make_mesh(...)`` /
        ``AbstractMesh(...)`` calls."""
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if terminal_name(node.func) not in (
                "Mesh",
                "make_mesh",
                "AbstractMesh",
            ):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    s = resolve_str(sub, ctx, self)
                    if s is not None:
                        self.declared_axes.add(s)


def terminal_name(node: ast.AST) -> Optional[str]:
    """`jax.experimental.shard_map.shard_map` -> "shard_map"; Name -> id."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """Full dotted path when the expression is a pure attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_str(
    node: ast.AST, ctx: FileContext, pkg: Optional["PackageContext"] = None
) -> Optional[str]:
    """Constant str, or a Name resolvable to a module-level / package-level
    string constant."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        if node.id in ctx.str_consts:
            return ctx.str_consts[node.id]
        if pkg is not None and node.id in pkg.str_consts:
            return pkg.str_consts[node.id]
    return None


def resolve_int(node: ast.AST, ctx: FileContext) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name) and node.id in ctx.int_consts:
        return ctx.int_consts[node.id]
    return None


# ---------------------------------------------------------------------------
# Runner


def iter_py_files(paths: Iterable[str], root: str = ".") -> List[str]:
    out: List[str] = []
    for p in paths:
        full = os.path.join(root, p) if not os.path.isabs(p) else p
        if os.path.isfile(full) and full.endswith(".py"):
            out.append(full)
        elif os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = sorted(
                    d
                    for d in dirnames
                    if not d.startswith(".") and d != "__pycache__"
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
    return out


def _run_rules(
    files: Sequence[FileContext], rules: Sequence
) -> Tuple[List[Finding], List[Finding]]:
    pkg = PackageContext(files)
    findings: List[Finding] = []
    parse_errors = [f.parse_error for f in files if f.parse_error is not None]
    for ctx in files:
        if ctx.tree is None:
            continue
        for rule in rules:
            for finding in rule.check(ctx, pkg):
                if not ctx.is_waived(
                    rule.id, rule.aliases, finding.line
                ):
                    findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, parse_errors


def lint_sources(
    sources: Sequence[Tuple[str, str]], rules: Optional[Sequence] = None
) -> LintResult:
    """In-memory entry point (what tests/test_lint.py drives):
    ``sources`` is [(relpath, source_text), ...]."""
    if rules is None:
        from tools.lint.rules import ALL_RULES as rules  # noqa: N811
    files = [FileContext(p, s) for p, s in sources]
    findings, parse_errors = _run_rules(files, rules)
    return LintResult(findings, list(findings), parse_errors)


def lint_paths(
    paths: Sequence[str],
    root: str = ".",
    baseline: Optional[dict] = None,
    rules: Optional[Sequence] = None,
) -> LintResult:
    if rules is None:
        from tools.lint.rules import ALL_RULES as rules  # noqa: N811
    files = []
    for fp in iter_py_files(paths, root):
        rel = os.path.relpath(fp, root)
        try:
            with open(fp, "r", encoding="utf-8") as fh:
                files.append(FileContext(rel, fh.read()))
        except (OSError, UnicodeDecodeError) as e:
            files.append(FileContext(rel, ""))
            files[-1].parse_error = Finding(
                "G000", rel.replace(os.sep, "/"), 1, 0, f"unreadable: {e}", ""
            )
    findings, parse_errors = _run_rules(files, rules)
    new = subtract_baseline(findings, baseline or {})
    return LintResult(findings, new, parse_errors)


# ---------------------------------------------------------------------------
# Baseline


def load_baseline(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or "fingerprints" not in data:
        raise ValueError(f"{path}: not a graftlint baseline file")
    return data


def make_baseline(findings: Sequence[Finding]) -> dict:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.fingerprint()] = counts.get(f.fingerprint(), 0) + 1
    return {
        "version": 1,
        "comment": (
            "Findings frozen at baseline time; the CLI fails only on "
            "findings beyond these counts.  Regenerate with "
            "`python -m tools.lint ... --write-baseline`."
        ),
        "fingerprints": dict(sorted(counts.items())),
    }


def subtract_baseline(
    findings: Sequence[Finding], baseline: dict
) -> List[Finding]:
    budget = dict(baseline.get("fingerprints", {}))
    new: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            new.append(f)
    return new
