import sys

from tools.lint.cli import main

sys.exit(main())
