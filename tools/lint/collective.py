"""graftlint v3: the SPMD collective-consistency substrate (ISSUE 13).

PR 12 made divergent collectives *survivable* (quorum consensus bounds a
mismatched collective into PeerLost); this layer makes them *provable*
at lint time.  Three pieces, all pure stdlib like graph/flow:

- **Static collective census** — every collective-issuing call site in
  the package (``psum``/``pmean``/``pmin``/``pmax``/``all_gather``/
  ``psum_scatter``/``all_to_all``/``ppermute``, plus multi-operand
  ``lax.sort`` — the comparator-exchange shape the sharded rule join
  uses), with its mesh axis, issuing engine path (module:function
  chain), and the enclosing branch conditions.  Ships in
  ``tools/lint/inventory.json`` as ``collective_sites`` under the same
  drift machinery as the fetch/failpoint censuses: adding, moving, or
  re-guarding a collective must ride its PR.

- **Collective-bearing closures** over the v2 call graph — which
  functions reach a collective dispatch.  Two variants: ``bearing_any``
  (plain reachability, G016's "does this chain walk sit on a collective
  path") and ``bearing_guarded``, which refuses to propagate through
  SYNC-CLAMPED functions (functions that run a ``quorum.sync``
  rendezvous themselves): a branch above ``fit()`` cannot diverge the
  mesh, because every rank re-exchanges positions at ``mine.start``
  before the first collective — that is the rendezvous-point-exchange
  sanitizer, applied structurally.

- **Chain declarations** — static parses of ``watchdog.CHAINS`` and
  ``quorum.CONSENSUS_CHAINS`` from the linted sources (the linter never
  imports the package), so G016 can drift-check the registration both
  ways against the live module text.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

# Communication-issuing collectives (G002's census minus the free
# ``axis_index``/``axis_size`` queries, which exchange nothing).
COLLECTIVE_NAMES = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "all_gather": 1,
    "psum_scatter": 1,
    "all_to_all": 1,
    "ppermute": 1,
}

_SYNC_TERMINAL = "sync"
_QUORUM_SYNC_FQ = "fastapriori_tpu.reliability.quorum.sync"


class CollectiveSite:
    """One censused collective call site."""

    __slots__ = ("name", "axis", "engine", "guards", "ctx", "node")

    def __init__(self, name, axis, engine, guards, ctx, node):
        self.name = name
        self.axis = axis
        self.engine = engine
        self.guards = guards
        self.ctx = ctx
        self.node = node

    def to_entry(self) -> dict:
        return {
            "collective": self.name,
            "axis": self.axis,
            "engine": self.engine,
            "guards": " && ".join(self.guards),
            "path": self.ctx.path,
        }


def _unparse(node: ast.AST, limit: int = 72) -> str:
    try:
        text = " ".join(ast.unparse(node).split())
    except (ValueError, RecursionError):  # pragma: no cover
        text = type(node).__name__
    return text if len(text) <= limit else text[: limit - 1] + "…"


def _is_multi_operand_sort(call: ast.Call) -> bool:
    """``lax.sort((a, b, ...), num_keys=K)`` — the multi-operand
    comparator sort the sharded rule join uses as an exchange layout.
    A plain single-array sort is local and free."""
    from tools.lint.engine import terminal_name

    if terminal_name(call.func) != "sort":
        return False
    for kw in call.keywords:
        if kw.arg == "num_keys":
            return True
    return bool(call.args) and isinstance(
        call.args[0], (ast.Tuple, ast.List)
    )


def _axis_of(call: ast.Call, ctx, pkg) -> str:
    """The collective's mesh axis: a resolved literal, the plumbing
    parameter's name (``param:axis_name``), or ``dynamic``."""
    from tools.lint.engine import terminal_name

    t = terminal_name(call.func)
    pos = COLLECTIVE_NAMES.get(t)
    expr: Optional[ast.AST] = None
    for kw in call.keywords:
        if kw.arg == "axis_name":
            expr = kw.value
    if expr is None and pos is not None and len(call.args) > pos:
        expr = call.args[pos]
    if expr is None:
        return ""
    if isinstance(expr, (ast.Tuple, ast.List)):
        parts = [_axis_of_single(e, ctx, pkg) for e in expr.elts]
        return ",".join(parts)
    return _axis_of_single(expr, ctx, pkg)


def _axis_of_single(expr: ast.AST, ctx, pkg) -> str:
    from tools.lint.engine import resolve_str, terminal_name

    s = resolve_str(expr, ctx, pkg)
    if s is not None:
        return s
    t = terminal_name(expr)
    if t is not None:
        return f"param:{t}"
    return "dynamic"


def census(pkg) -> List[CollectiveSite]:
    """Every collective site in every NON-TEST file, with engine path
    and guard stack (cached per run)."""
    cached = getattr(pkg, "_collective_census", None)
    if cached is not None:
        return cached
    from tools.lint.engine import is_test_path, terminal_name
    from tools.lint.graph import module_name

    out: List[CollectiveSite] = []

    def visit(node, ctx, fn_chain, guards):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_chain = fn_chain + [node.name]
            guards = []
        elif isinstance(node, ast.If):
            cond = _unparse(node.test)
            visit(node.test, ctx, fn_chain, guards)
            for child in node.body:
                visit(child, ctx, fn_chain, guards + [cond])
            for child in node.orelse:
                visit(child, ctx, fn_chain, guards + [f"not ({cond})"])
            return
        elif isinstance(node, ast.IfExp):
            cond = _unparse(node.test)
            visit(node.test, ctx, fn_chain, guards)
            visit(node.body, ctx, fn_chain, guards + [cond])
            visit(node.orelse, ctx, fn_chain, guards + [f"not ({cond})"])
            return
        elif isinstance(node, (ast.While, ast.For)):
            header = (
                f"while {_unparse(node.test)}"
                if isinstance(node, ast.While)
                else f"for {_unparse(node.target)}"
            )
            for child in ast.iter_child_nodes(node):
                in_suite = child in node.body or child in node.orelse
                visit(
                    child,
                    ctx,
                    fn_chain,
                    guards + [header] if in_suite else guards,
                )
            return
        elif isinstance(node, ast.ExceptHandler):
            what = _unparse(node.type) if node.type is not None else ""
            guards = guards + [f"except {what}".rstrip()]
        elif isinstance(node, ast.Call):
            t = terminal_name(node.func)
            if t in COLLECTIVE_NAMES or _is_multi_operand_sort(node):
                engine = module_name(ctx.path) + ":" + ".".join(
                    fn_chain or ["<module>"]
                )
                out.append(
                    CollectiveSite(
                        "sort" if t == "sort" else t,
                        _axis_of(node, ctx, pkg),
                        engine,
                        list(guards),
                        ctx,
                        node,
                    )
                )
        for child in ast.iter_child_nodes(node):
            visit(child, ctx, fn_chain, guards)

    for ctx in pkg.files:
        if ctx.tree is None or is_test_path(ctx.path):
            continue
        # Cheap pre-filter: most files name no collective at all.
        if not any(
            name in ctx.source for name in COLLECTIVE_NAMES
        ) and "sort" not in ctx.source:
            continue
        for stmt in ctx.tree.body:
            visit(stmt, ctx, [], [])
    pkg._collective_census = out
    return out


# ---------------------------------------------------------------------------
# sync detection + collective-bearing closures


def is_sync_call(call: ast.Call, ctx, pkg) -> bool:
    """A ``quorum.sync`` position-vector exchange (rendezvous point).
    Matched by the resolved fully-qualified name, the dotted
    ``quorum.sync`` spelling, or a bare ``sync`` imported from the
    quorum module."""
    from tools.lint.engine import dotted_name

    d = dotted_name(call.func)
    if d is None:
        return False
    if d == _SYNC_TERMINAL or d.endswith(".sync"):
        fq = pkg.graph.resolve_expr(ctx, call.func)
        if fq == _QUORUM_SYNC_FQ:
            return True
        return d.endswith("quorum.sync") or (
            fq is not None and fq.endswith("quorum.sync")
        )
    return False


def _fn_has_sync(fn: ast.AST, ctx, pkg) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and is_sync_call(node, ctx, pkg):
            return True
    return False


def sync_clamped(pkg) -> Set[str]:
    """Fully-qualified names of functions that run a position-vector
    exchange themselves (cached per run)."""
    cached = getattr(pkg, "_sync_clamped", None)
    if cached is not None:
        return cached
    out: Set[str] = set()
    for ctx in pkg.files:
        table = pkg.graph.by_path.get(ctx.path)
        if table is None or ctx.tree is None:
            continue
        if "sync" not in ctx.source:
            continue
        for local, fn in table.functions.items():
            if _fn_has_sync(fn, ctx, pkg):
                out.add(f"{table.name}.{local}")
    pkg._sync_clamped = out
    return out


def _direct_collective_fns(pkg) -> Set[str]:
    from tools.lint.engine import terminal_name

    out: Set[str] = set()
    sites = census(pkg)
    by_path: Dict[str, List[CollectiveSite]] = {}
    for s in sites:
        by_path.setdefault(s.ctx.path, []).append(s)
    for path, file_sites in by_path.items():
        table = pkg.graph.by_path.get(path)
        if table is None:
            continue
        site_ids = {id(s.node) for s in file_sites}
        for local, fn in table.functions.items():
            for node in ast.walk(fn):
                if id(node) in site_ids:
                    out.add(f"{table.name}.{local}")
                    break
    return out


def callee_map(pkg) -> Dict[str, Set[str]]:
    """``fq function -> resolvable callee fqs`` over the whole package
    (cached per run — graph resolution is the expensive part of every
    closure, and both bearing variants share it)."""
    cached = getattr(pkg, "_callee_map", None)
    if cached is not None:
        return cached
    out: Dict[str, Set[str]] = {}
    for ctx in pkg.files:
        table = pkg.graph.by_path.get(ctx.path)
        if table is None or ctx.tree is None:
            continue
        for local, fn in table.functions.items():
            qual = f"{table.name}.{local}"
            callees: Set[str] = set()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                fq = pkg.graph.resolve_call_fq(ctx, node)
                if fq is not None:
                    callees.add(fq)
            out[qual] = callees
    pkg._callee_map = out
    return out


def _bearing_closure(pkg, barrier: bool) -> Set[str]:
    """Fixpoint reachability: a function bears collectives when it
    censuses one directly or calls a bearing function.  With
    ``barrier`` set, reachability refuses to cross SYNC-CLAMPED callees
    (their entry rendezvous re-uniforms the mesh before the collective
    dispatches)."""
    bearing = set(_direct_collective_fns(pkg))
    clamped = sync_clamped(pkg) if barrier else set()
    # A sync-clamped function's own collectives sit BEHIND its
    # rendezvous: callers reaching only them cannot diverge the mesh.
    bearing -= clamped
    calls = callee_map(pkg)
    changed = True
    while changed:
        changed = False
        for qual, callees in calls.items():
            if qual in bearing:
                continue
            for callee in callees:
                if callee in bearing and callee not in clamped:
                    bearing.add(qual)
                    changed = True
                    break
    return bearing


def bearing_any(pkg) -> Set[str]:
    """Functions from which a collective dispatch is reachable (no
    barriers) — G016's reachability predicate."""
    cached = getattr(pkg, "_bearing_any", None)
    if cached is None:
        cached = pkg._bearing_any = _bearing_closure(pkg, barrier=False)
    return cached


def bearing_guarded(pkg) -> Set[str]:
    """Reachability that stops at sync-clamped callees — G015's
    predicate: a divergent branch only matters when a collective can
    dispatch before the next rendezvous re-uniforms the mesh."""
    cached = getattr(pkg, "_bearing_guarded", None)
    if cached is None:
        cached = pkg._bearing_guarded = _bearing_closure(pkg, barrier=True)
    return cached


# ---------------------------------------------------------------------------
# chain declarations (static parses of the live modules)


def chains_decl(pkg) -> Dict[str, Tuple]:
    """``chain -> (stage order, ctx, dict-key node)`` parsed from the
    ``CHAINS = {...}`` assignment (reliability/watchdog.py in the real
    tree).  Empty when the linted tree declares none."""
    cached = getattr(pkg, "_chains_decl", None)
    if cached is not None:
        return cached
    out: Dict[str, Tuple] = {}
    for ctx in pkg.files:
        if ctx.tree is None:
            continue
        for node in ctx.nodes(ast.Assign, ast.AnnAssign):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            if not any(
                isinstance(t, ast.Name) and t.id == "CHAINS"
                for t in targets
            ):
                continue
            value = node.value
            if not isinstance(value, ast.Dict):
                continue
            for key, val in zip(value.keys, value.values):
                if not (
                    isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                ):
                    continue
                stages = tuple(
                    e.value
                    for e in ast.walk(val)
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                )
                out[key.value] = (stages, ctx, key)
    pkg._chains_decl = out
    return out


def consensus_decl(pkg) -> Dict[str, Tuple]:
    """``chain -> (ctx, element node)`` parsed from the
    ``CONSENSUS_CHAINS = (...)`` assignment (reliability/quorum.py)."""
    cached = getattr(pkg, "_consensus_decl", None)
    if cached is not None:
        return cached
    out: Dict[str, Tuple] = {}
    for ctx in pkg.files:
        if ctx.tree is None:
            continue
        for node in ctx.nodes(ast.Assign, ast.AnnAssign):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            if not any(
                isinstance(t, ast.Name) and t.id == "CONSENSUS_CHAINS"
                for t in targets
            ):
                continue
            if node.value is None:
                continue
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str
                ):
                    out[sub.value] = (ctx, sub)
    pkg._consensus_decl = out
    return out


def consensus_chain_names(pkg) -> Optional[Set[str]]:
    """The registered chain-name set, or None when the linted tree
    declares no CONSENSUS_CHAINS (fixture packages — every downgrade
    then counts as a sanitizer; there is no registry to hold it to)."""
    decl = consensus_decl(pkg)
    return set(decl) if decl else None


_CHAIN_WALK_TERMINALS = {
    "stage_allowed",
    "floor_stage",
    "propose",
    "downgrade",
}


def chain_walk_calls(pkg) -> List[Tuple[str, object, ast.Call, str]]:
    """Every ``stage_allowed``/``floor_stage``/``propose``/``downgrade``
    call with a resolvable chain-name first argument, as
    ``(chain, ctx, call, enclosing-fn-qualname-or-"")`` over NON-TEST
    files (cached per run)."""
    cached = getattr(pkg, "_chain_walk_calls", None)
    if cached is not None:
        return cached
    from tools.lint.engine import is_test_path, resolve_str, terminal_name

    out: List[Tuple[str, object, ast.Call, str]] = []
    for ctx in pkg.files:
        if ctx.tree is None or is_test_path(ctx.path):
            continue
        table = pkg.graph.by_path.get(ctx.path)
        enclosing = ctx.enclosing_functions()
        fn_names: Dict[int, str] = {}
        if table is not None:
            for local, fn in table.functions.items():
                fn_names[id(fn)] = f"{table.name}.{local}"
        for node in ctx.nodes(ast.Call):
            if terminal_name(node.func) not in _CHAIN_WALK_TERMINALS:
                continue
            if not node.args:
                continue
            chain = resolve_str(node.args[0], ctx, pkg)
            if chain is None:
                continue
            fn = enclosing.get(id(node))
            qual = fn_names.get(id(fn), "") if fn is not None else ""
            out.append((chain, ctx, node, qual))
    pkg._chain_walk_calls = out
    return out


def downgrade_sites(pkg) -> List[Tuple[str, object, object, object, ast.Call]]:
    """Every ``downgrade(chain, frm, to, ...)`` call with a resolvable
    chain-name first argument, as ``(chain, frm, to, ctx, call)`` over
    NON-TEST files (cached per run).  ``frm``/``to`` are None when the
    stage argument is dynamic — the quorum adoption loop forwards the
    exchanged positions through variables; G019 holds only LITERAL
    walks to the declaration (a dynamic stage is validated at runtime
    by watchdog.downgrade itself)."""
    cached = getattr(pkg, "_downgrade_sites", None)
    if cached is not None:
        return cached
    from tools.lint.engine import is_test_path, resolve_str, terminal_name

    out: List[Tuple[str, object, object, object, ast.Call]] = []
    for ctx in pkg.files:
        if ctx.tree is None or is_test_path(ctx.path):
            continue
        if "downgrade" not in ctx.source:
            continue
        for node in ctx.nodes(ast.Call):
            if terminal_name(node.func) != "downgrade":
                continue
            if not node.args:
                continue
            chain = resolve_str(node.args[0], ctx, pkg)
            if chain is None:
                continue
            frm = (
                resolve_str(node.args[1], ctx, pkg)
                if len(node.args) > 1
                else None
            )
            to = (
                resolve_str(node.args[2], ctx, pkg)
                if len(node.args) > 2
                else None
            )
            for kw in node.keywords:
                if kw.arg == "frm":
                    frm = resolve_str(kw.value, ctx, pkg)
                elif kw.arg == "to":
                    to = resolve_str(kw.value, ctx, pkg)
            out.append((chain, frm, to, ctx, node))
    pkg._downgrade_sites = out
    return out
