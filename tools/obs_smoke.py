"""Bounded observability smoke (ISSUE 11 satellite; `make obs-smoke`).

Drives the tracing + metrics + flight-recorder substrate end to end on
the CI corpus, in one wall-budgeted pass:

1. **mine under --trace**: the full CLI pipeline exports a Chrome-trace
   artifact that loads (json + schema via the shared
   ``obs.trace.validate_chrome_trace``) and carries the span hierarchy
   (a ``mine`` root, level/fused work, audited ``fetch.*`` spans) plus
   at least one counter event (collective bytes).
2. **serve under --trace + --metrics-dump**: the serve CLI's artifact
   carries ``serve.batch`` spans whose children split host work
   (dedup/pack or the host scan) from device/scan time, and the
   periodic metrics dump parses as Prometheus text.
3. **mid-burst scrape**: a live server is scraped WHILE requests are in
   flight — ``metrics_text()`` returns a parseable snapshot whose
   counters move between scrapes.
4. **tracing-off overhead ≈ 0**: with the tracer disabled, a mine
   records ZERO events and 100K disabled span entries cost well under a
   millisecond each (the near-zero-cost contract the serve bench's
   no-obs control bounds end to end).

Run: ``env JAX_PLATFORMS=cpu python tools/obs_smoke.py``.
Exit 0 = all invariants held.  Wall time is logged by tools/ci.sh
against its budget, like lint's and the serve smoke's.
"""

from __future__ import annotations

import json
import os
import random
import re
import sys
import tempfile
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # `python tools/obs_smoke.py`
    sys.path.insert(0, _REPO_ROOT)

os.environ.setdefault("FA_NO_COMPILE_LOG", "1")

_PROM_LINE = re.compile(
    r"^(#.*|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.e+-]+|)$"
)


def make_inputs(root: str) -> str:
    """Deterministic tiny corpus (the serve smoke's shape)."""
    rng = random.Random(11)
    items = [str(i) for i in range(1, 13)]
    weights = [1.0 / (i + 1) for i in range(12)]
    lines = [
        " ".join(rng.choices(items, weights=weights, k=rng.randint(1, 6)))
        for _ in range(130)
    ] + ["1 2 3 4 5"] * 20
    inp = os.path.join(root, "in") + os.sep
    os.makedirs(inp)
    # lint: waive G009 -- smoke INPUT fixtures in a fresh temp dir, not run artifacts
    with open(os.path.join(inp, "D.dat"), "w") as f:
        f.writelines(l + "\n" for l in lines)
    # lint: waive G009 -- smoke INPUT fixtures in a fresh temp dir, not run artifacts
    with open(os.path.join(inp, "U.dat"), "w") as f:
        f.writelines(l + "\n" for l in lines[:30])
    return inp


def main() -> int:
    t_start = time.time()
    from fastapriori_tpu.cli import main as cli_main
    from fastapriori_tpu.obs import trace
    from fastapriori_tpu.obs.trace import TRACER, validate_chrome_trace

    failures = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        status = "ok" if ok else "FAIL"
        print(f"obs-smoke [{name}] {status} {detail}".rstrip())
        if not ok:
            failures.append(name)

    with tempfile.TemporaryDirectory() as root:
        inp = make_inputs(root)
        out = os.path.join(root, "out") + os.sep
        os.makedirs(out)

        # 1. mine under --trace -> Perfetto-loadable artifact with the
        # span hierarchy + a counter track.
        mine_trace = os.path.join(root, "mine.trace.json")
        rc = cli_main(
            [inp, out, "--min-support", "0.08", "--trace", mine_trace]
        )
        with open(mine_trace) as fh:
            obj = json.load(fh)
        problems = validate_chrome_trace(obj)
        names = {e["name"] for e in obj["traceEvents"]}
        spans = [e for e in obj["traceEvents"] if e["ph"] == "X"]
        check(
            "mine-trace",
            rc == 0 and not problems and len(spans) >= 5,
            f"{len(obj['traceEvents'])} events, {len(problems)} "
            "schema problem(s)",
        )
        check(
            "mine-trace-hierarchy",
            "mine" in names
            and any(n.startswith("fetch.") for n in names)
            and any(e["ph"] == "C" for e in obj["traceEvents"]),
            f"names={sorted(names)[:8]}...",
        )
        # Nesting: at least one span's sid is prefixed by another's.
        sids = sorted(e["args"]["sid"] for e in spans)
        nested = any(
            b.startswith(a + "/") for a in sids for b in sids if a != b
        )
        check("mine-trace-nesting", nested, f"{len(sids)} spans")

        # 2. serve under --trace + --metrics-dump.
        serve_trace = os.path.join(root, "serve.trace.json")
        dump_path = os.path.join(root, "metrics.prom")
        rc = cli_main(
            [
                "serve", inp, out, "--min-support", "0.08",
                "--trace", serve_trace, "--metrics-dump", dump_path,
            ]
        )
        with open(serve_trace) as fh:
            sobj = json.load(fh)
        sproblems = validate_chrome_trace(sobj)
        snames = {e["name"] for e in sobj["traceEvents"]}
        batch_ok = "serve.batch" in snames and (
            {"serve.pack", "serve.scan"} <= snames
            or "serve.host_scan" in snames
        )
        check(
            "serve-trace",
            rc == 0 and not sproblems and batch_ok,
            f"serve spans: {sorted(n for n in snames if 'serve' in n)}",
        )
        with open(dump_path) as fh:
            prom = fh.read()
        bad = [
            l for l in prom.splitlines() if not _PROM_LINE.match(l)
        ]
        check(
            "metrics-dump",
            "fa_serve_served_total" in prom and not bad,
            f"{len(prom.splitlines())} lines, {len(bad)} unparseable",
        )

        # 3. mid-burst scrape: counters move while requests are in
        # flight.
        from fastapriori_tpu.config import MinerConfig
        from fastapriori_tpu.io.reader import tokenize_line
        from fastapriori_tpu.serve import RecommendServer, ServingState

        with open(os.path.join(inp, "D.dat")) as f:
            pool = [tokenize_line(l) for l in f][:40]
        cfg = MinerConfig(min_support=0.08, retain_csr=False)
        state = ServingState.from_mine(
            os.path.join(inp, "D.dat"), config=cfg
        )
        server = RecommendServer(
            state, batch_rows=32, linger_ms=2.0, queue_depth=4096
        ).start()
        reqs = [server.submit(t) for t in pool * 10]
        mid = server.metrics_text()  # scraped mid-burst, by design
        mid_bad = [
            l for l in mid.splitlines() if not _PROM_LINE.match(l)
        ]
        server.wait_for(reqs, timeout_s=60.0)
        after = server.metrics_text()
        server.stop(drain=True)

        def counter_val(text: str, name: str) -> float:
            for line in text.splitlines():
                if line.startswith(name + " "):
                    return float(line.split()[-1])
            return -1.0

        check(
            "mid-burst-scrape",
            not mid_bad
            and counter_val(mid, "fa_serve_submitted_total")
            == len(reqs)
            and counter_val(after, "fa_serve_served_total")
            + counter_val(after, "fa_serve_shed_total")
            == len(reqs),
            f"submitted {counter_val(mid, 'fa_serve_submitted_total')},"
            f" served {counter_val(after, 'fa_serve_served_total')}",
        )

        # 3b. forced-device server under tracing: the serve.batch span's
        # children separate host work (serve.dedup/serve.pack) from the
        # device scan wait (serve.scan + the audited fetch.serve_match
        # span inside it) — the ISSUE 11 acceptance split.
        dev_state = ServingState.from_mine(
            os.path.join(inp, "D.dat"), config=cfg, engine="device"
        )
        TRACER.enable()
        dev_server = RecommendServer(
            dev_state, batch_rows=32, linger_ms=1.0, queue_depth=4096
        ).start()
        dreqs = [dev_server.submit(t) for t in pool * 4]
        dev_server.wait_for(dreqs, timeout_s=60.0)
        dev_server.stop(drain=True)
        dnames = {name for _, name, _ in TRACER.span_tree()}
        TRACER.disable()
        check(
            "device-span-split",
            {"serve.batch", "serve.dedup", "serve.pack", "serve.scan",
             "fetch.serve_match"} <= dnames,
            f"{sorted(n for n in dnames if 'serve' in n)}",
        )

        # 4. tracing-off overhead ~ 0: a disabled mine records nothing,
        # and the disabled span entry point is branch-cheap.
        TRACER.disable()
        TRACER.reset()
        rc = cli_main(
            [inp, os.path.join(root, "out2") + os.sep, "--min-support",
             "0.08"]
        )
        check(
            "tracing-off-no-events",
            rc == 0 and not TRACER.events() and not TRACER.enabled,
            f"{len(TRACER.events())} events recorded while disabled",
        )
        t0 = time.perf_counter()
        for _ in range(100_000):
            with trace.span("x"):
                pass
        per_call_us = (time.perf_counter() - t0) * 1e6 / 100_000
        check(
            "tracing-off-cheap",
            per_call_us < 10.0,
            f"{per_call_us:.2f}us per disabled span (bound 10us)",
        )

    wall = time.time() - t_start
    print(f"obs-smoke: wall {wall:.1f}s, {len(failures)} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
