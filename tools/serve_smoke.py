"""Bounded serving-tier smoke (ISSUE 10 satellite; `make serve-smoke`).

Stands the resident recommend server up on the CI corpus and drives it
through the serving invariants end to end, in one wall-budgeted pass:

1. **Build + warm restart**: mine the corpus, serve a fixed request
   set, checkpoint the ServingState, reload it (manifest-validated) and
   assert the restarted server answers byte-identically.
2. **Sustained open-loop burst**: a seeded arrival schedule below
   capacity — everything serves, latency percentiles are finite, the
   run drains inside the bound (never a hang).
3. **Overload spike**: a slow-scan failpoint (``fetch.serve_match
   delay``) plus a burst far past capacity against a tiny queue —
   admission control must SHED (answered "0" + the serving cascade
   event on the ledger), the queue stays bounded, and the server
   recovers: a post-spike request set serves normally again.
4. **Transient absorb**: ``fetch.serve_match:oom*1`` — the audited
   fetch's retry absorbs one injected failure, responses stay correct,
   the ledger names the site.

Run: ``env JAX_PLATFORMS=cpu python tools/serve_smoke.py``.
Exit 0 = all invariants held.  Wall time is logged by tools/ci.sh
against its budget, like lint's and the chaos soak's.
"""

from __future__ import annotations

import os
import random
import sys
import tempfile
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # `python tools/serve_smoke.py`
    sys.path.insert(0, _REPO_ROOT)


def make_inputs(root: str) -> str:
    """Deterministic tiny corpus (the chaos soak's shape)."""
    rng = random.Random(11)
    items = [str(i) for i in range(1, 13)]
    weights = [1.0 / (i + 1) for i in range(12)]
    lines = [
        " ".join(rng.choices(items, weights=weights, k=rng.randint(1, 6)))
        for _ in range(130)
    ] + ["1 2 3 4 5"] * 20
    inp = os.path.join(root, "in") + os.sep
    os.makedirs(inp)
    # lint: waive G009 -- smoke INPUT fixtures in a fresh temp dir, not run artifacts
    with open(os.path.join(inp, "D.dat"), "w") as f:
        f.writelines(l + "\n" for l in lines)
    return inp


def main() -> int:
    t_start = time.time()
    from fastapriori_tpu.config import MinerConfig
    from fastapriori_tpu.io.reader import tokenize_line
    from fastapriori_tpu.reliability import failpoints, ledger
    from fastapriori_tpu.serve import (
        RecommendServer,
        ServingState,
        run_open_loop,
    )

    failures = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        status = "ok" if ok else "FAIL"
        print(f"serve-smoke [{name}] {status} {detail}".rstrip())
        if not ok:
            failures.append(name)

    with tempfile.TemporaryDirectory() as root:
        inp = make_inputs(root)
        out = os.path.join(root, "out") + os.sep
        os.makedirs(out)
        with open(os.path.join(inp, "D.dat")) as f:
            pool = [tokenize_line(l) for l in f][:40]

        cfg = MinerConfig(min_support=0.1, retain_csr=False)
        state = ServingState.from_mine(
            os.path.join(inp, "D.dat"), config=cfg
        )
        state.warm()
        baseline = state.recommend_batch(pool)
        check(
            "build",
            state.n_rules > 0 and len(baseline) == len(pool),
            f"{state.n_rules} rules, engine {state.describe()['engine']}",
        )

        # 1. checkpoint -> reload -> byte-identical.
        state.save(out)
        restored = ServingState.load(out, config=cfg)
        check(
            "warm-restart",
            restored.signature == state.signature
            and restored.recommend_batch(pool) == baseline,
            f"signature {restored.signature}",
        )

        # The server scenarios run the DEVICE engine (forced — the CI
        # model is below the auto threshold) so the audited
        # fetch.serve_match site is genuinely on the hot path for the
        # delay/oom injections below; device responses must equal the
        # host baseline.
        dev_state = ServingState.load(out, config=cfg, engine="device")
        dev_state.warm()
        check(
            "device-vs-host",
            dev_state.recommend_batch(pool) == baseline,
            f"resident={dev_state.describe().get('resident_table')}",
        )

        # 2. sustained seeded burst below capacity: all served, finite
        # percentiles, bounded drain.
        ledger.reset()
        server = RecommendServer(
            dev_state, batch_rows=32, linger_ms=1.0, queue_depth=4096
        ).start(warm=False)
        sustained = run_open_loop(
            server, pool, rate_rps=500.0, n_requests=600, seed=7,
            drain_timeout_s=60.0, label="sustained",
        )
        check(
            "sustained",
            sustained["drained"]
            and sustained["served"] + sustained["shed"] == 600
            and sustained["p99_ms"] is not None,
            f"achieved {sustained['achieved_rps']}/s "
            f"p99 {sustained['p99_ms']}ms shed {sustained['shed']}",
        )

        # 3. overload spike: slow scans (armed delay on the serving
        # fetch) + a tiny queue + a burst far past capacity -> sheds
        # recorded, queue bounded, no hang.
        server.stop(drain=True)
        failpoints.arm("fetch.serve_match", "delay@25")
        slow = RecommendServer(
            dev_state, batch_rows=32, linger_ms=0.0, queue_depth=64
        ).start(warm=False)
        overload = run_open_loop(
            slow, pool, rate_rps=20000.0, n_requests=4000, seed=8,
            drain_timeout_s=60.0, label="overload",
        )
        failpoints.disarm_all()
        shed_reqs = overload["shed"]
        cascade = [
            e for e in ledger.snapshot()
            if e.get("kind") == "cascade" and e.get("chain") == "serving"
        ]
        check(
            "overload-sheds",
            shed_reqs > 0 and overload["drained"] and len(cascade) >= 1,
            f"shed {shed_reqs}/4000, max_queue {overload['max_queue']} "
            f"(bound 64), cascade events {len(cascade)}",
        )
        check(
            "overload-bounded",
            overload["max_queue"] <= 64
            and overload["served"] + shed_reqs == 4000,
        )
        # Recovery: after the spike (failpoint disarmed), the same
        # server serves a normal request set byte-identically.
        recovery = [slow.submit_wait(t, timeout_s=30.0) for t in pool]
        slow.wait_for(recovery, timeout_s=60.0)
        check(
            "recovery",
            [r.item for r in recovery] == baseline,
            "post-spike responses byte-identical",
        )
        stopped = slow.stop(drain=True)
        check("stop", stopped, "dispatcher exited inside the bound")

        # 4. transient absorb on the audited serving fetch: one injected
        # OOM is retried away, responses stay correct, the ledger names
        # the site.
        ledger.reset()
        failpoints.arm("fetch.serve_match", "oom*1")
        again = dev_state.recommend_batch(pool)
        retries = [
            e for e in ledger.snapshot()
            if e.get("kind") == "retry"
            and e.get("site") == "fetch.serve_match"
        ]
        failpoints.disarm_all()
        check(
            "transient-absorb",
            again == baseline and len(retries) >= 1,
            f"retries {len(retries)}",
        )

        # 5. mesh leg (ISSUE 19): two SUBPROCESS hosts behind the
        # request router, serving the same checkpoint — responses match
        # the single-host baseline, per-host metrics merge into one
        # mesh surface, and killing one host mid-burst drains its share
        # to the survivor as recorded sheds (never a hang).
        from fastapriori_tpu.serve import MeshRouter, ProcHost

        mesh_dir = os.path.join(root, "mesh")
        hosts = [
            ProcHost(
                f"w{i}",
                os.path.join(mesh_dir, f"w{i}"),
                out,
                queue_depth=512,
                env={"JAX_PLATFORMS": "cpu"},
            )
            for i in range(2)
        ]
        mesh = MeshRouter(hosts)
        reqs = [mesh.submit(t) for t in pool]
        drained = mesh.wait_for(reqs, timeout_s=60.0)
        served_total = None
        if drained:
            deadline = time.time() + 5.0
            while time.time() < deadline:  # stats ride the poller; lag
                served_total = mesh.metrics_snapshot().get(
                    "fa_serve_served_total"
                )
                if served_total == len(pool):
                    break
                time.sleep(0.05)
        check(
            "mesh-serve",
            drained
            and [r.item for r in reqs] == baseline
            and served_total == len(pool),
            f"2 hosts, {len(pool)} requests, merged "
            f"fa_serve_served_total {served_total}",
        )
        burst = [pool[i % len(pool)] for i in range(200)]
        reqs2 = []
        for i, t in enumerate(burst):
            reqs2.append(mesh.submit(t))
            if i == 60:
                hosts[0].kill()  # abrupt death mid-burst
        done = mesh.wait_for(reqs2, timeout_s=60.0)
        st = mesh.stats()
        wrong = sum(
            1
            for i, r in enumerate(reqs2)
            if not r.shed and r.item != baseline[i % len(pool)]
        )
        check(
            "mesh-kill",
            done
            and all(r.done for r in reqs2)
            and st["hosts_lost"] == 1
            and wrong == 0,
            f"lost {st['hosts_lost']} host, shed {st['shed']} "
            f"(lost-shed {st['lost_shed']}), 0 wrong responses",
        )
        check("mesh-stop", mesh.stop(), "mesh exited inside the bound")

    wall = time.time() - t_start
    print(f"serve-smoke: wall {wall:.1f}s, {len(failures)} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
