"""Seeded chaos soak harness (ISSUE 9): deterministic randomized
failpoint schedules against the full mine+rules+recommend pipeline.

The point tests (tests/test_reliability.py, tools/failpoint_smoke.py)
each arm ONE hand-picked site; with 20+ audited fetch sites and a
five-deep engine-fallback stack the *interaction* space is far larger
than they cover.  This harness derives schedules from a seeded RNG over
``sites × kinds × counts`` — the site list comes from the lint-censused
contract inventory (``tools/lint/inventory.json``), so every NEW fetch
site is auto-enrolled the moment the census regenerates — and runs the
real CLI pipeline under each, asserting the global invariant:

    Every scenario ends in exactly one of:
      1. **byte-identical output** to the clean run (degradations
         allowed — they are counted from the ledger);
      2. a **classified error naming the site** (InputError exit 2, a
         transient/injected status error, or the InjectedAbort kill
         stand-in — after which a checkpointed run must resume
         byte-identically and a truncated artifact must be REJECTED by
         manifest validation);
      3. never anything else: a hang (per-scenario wall bound enforced
         from a watchdog thread), silent corruption (different bytes
         with rc 0 and no truncation armed), or an unclassified crash
         (any exception outside the classification contract).

Same seed → same schedule → same outcome (asserted by
tests/test_reliability.py); the CI soak (`make chaos`, tools/ci.sh)
runs a fixed seed set under a wall budget and logs its wall time like
lint's 10 s budget.

Usage::

    python tools/chaos.py [--seeds 0,1,2,3] [--scenarios 4]
                          [--budget-s 120] [--scenario-timeout-s 90]

``FA_CHAOS_SEED`` (strict int) offsets the whole seed set — the knob
for soaking a different schedule region without editing the CI set.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # `python tools/chaos.py`
    sys.path.insert(0, _REPO_ROOT)

_INVENTORY = os.path.join(_REPO_ROOT, "tools", "lint", "inventory.json")

# Non-fetch sites worth soaking that the fetch census cannot enroll:
# artifact writes (truncate/io), the post-commit level hooks (the
# kill-and-resume kill points), the mid-mine drain, and the native
# loader.  Kept small and explicit — fetch sites auto-enroll.
EXTRA_SITES: Dict[str, Tuple[str, ...]] = {
    "write.freqItemset": ("truncate", "io", "delay"),
    "write.freqItems": ("truncate", "delay"),
    "write.checkpoint.npz": ("truncate", "io", "delay"),
    "level.2": ("abort", "delay"),
    "level.3": ("abort", "delay"),
    "level.4": ("abort",),
    "drain.counts": ("oom", "delay"),
    "native.load": ("io",),
    "rules.upload": ("oom", "delay"),
}

_FETCH_KINDS = ("oom*1", "oom*2", "oom", "io", "delay")


def fetch_sites_from_inventory(path: str = _INVENTORY) -> List[str]:
    """``fetch.<label>`` for every censused fetch site — the
    auto-enrollment contract: a new audited fetch site enters the soak
    the moment ``--write-inventory`` regenerates the census."""
    with open(path) as fh:
        inv = json.load(fh)
    return sorted(
        "fetch." + e["label"] for e in inv.get("fetch_sites", [])
    )


def enrolled_sites(path: str = _INVENTORY) -> Dict[str, Tuple[str, ...]]:
    """site -> candidate kinds, fetch census + the explicit extras."""
    out: Dict[str, Tuple[str, ...]] = {
        s: _FETCH_KINDS for s in fetch_sites_from_inventory(path)
    }
    out.update(EXTRA_SITES)
    return out


def make_schedule(seed: int, sites: Optional[Dict] = None) -> dict:
    """ONE deterministic scenario from ``seed``: armed failpoint specs
    plus the pipeline shape to run them under.  Pure function of the
    seed and the (sorted) site inventory — tests pin same-seed
    equality."""
    if sites is None:
        sites = enrolled_sites()
    rng = random.Random(seed)
    n = rng.randint(1, 3)
    armed: Dict[str, str] = {}
    for site in rng.sample(sorted(sites), n):
        kind = rng.choice(sites[site])
        if kind == "delay":
            spec = f"delay@{rng.randint(1, 25)}"
        elif kind == "truncate":
            spec = f"truncate@{rng.randint(5, 60)}"
        elif kind == "oom" and rng.random() < 0.5:
            spec = f"oom*{rng.randint(1, 2)}"
        else:
            spec = kind
        armed[site] = spec
    has_abort = any(s.startswith("abort") for s in armed.values())
    checkpoint = has_abort or rng.random() < 0.4
    engine = rng.choice(("auto", "level", "fused"))
    return {
        "seed": seed,
        "failpoints": armed,
        "engine": engine,
        "checkpoint": checkpoint,
        "cadence": rng.choice((1, 2, 3)) if checkpoint else 1,
    }


def _base_seed() -> int:
    """``FA_CHAOS_SEED`` offset for the whole seed set — strict parse
    (the FA_NO_PALLAS contract: a typo'd seed silently soaking seed 0
    would report coverage that never ran)."""
    from fastapriori_tpu.utils.env import env_int

    return env_int("FA_CHAOS_SEED", 0, minimum=0)


def make_inputs(root: str) -> str:
    """Deterministic tiny corpus (the failpoint_smoke shape, plus a
    planted deep itemset so multi-segment fused-checkpoint schedules
    exercise more than one segment)."""
    rng = random.Random(11)
    items = [str(i) for i in range(1, 13)]
    weights = [1.0 / (i + 1) for i in range(12)]
    lines = [
        " ".join(rng.choices(items, weights=weights, k=rng.randint(1, 6)))
        for _ in range(130)
    ] + ["1 2 3 4 5"] * 20
    inp = os.path.join(root, "in") + os.sep
    os.makedirs(inp)
    # lint: waive G009 -- soak INPUT fixtures in a fresh temp dir, not run artifacts
    with open(os.path.join(inp, "D.dat"), "w") as f:
        f.writelines(l + "\n" for l in lines)
    # lint: waive G009 -- soak INPUT fixtures in a fresh temp dir, not run artifacts
    with open(os.path.join(inp, "U.dat"), "w") as f:
        f.writelines(l + "\n" for l in lines[:25])
    return inp


class Outcome:
    __slots__ = ("kind", "detail")

    def __init__(self, kind: str, detail: str = ""):
        self.kind = kind  # identical | classified | killed_resumed | FAIL
        self.detail = detail


def _run_cli_bounded(argv: List[str], timeout_s: float):
    """cli.main on a worker thread with a wall bound — the harness-side
    no-hang assertion (the in-process analog of the dispatch watchdog).
    Returns ``(rc_or_None, exception_or_None, hung)``."""
    from fastapriori_tpu.cli import main

    box: list = []

    def run() -> None:
        try:
            box.append(("rc", main(argv)))
        # lint: waive G006 -- captured (InjectedAbort is a BaseException) and judged against the invariant by the caller
        except BaseException as exc:
            box.append(("err", exc))

    worker = threading.Thread(target=run, daemon=True)
    worker.start()
    worker.join(timeout_s)
    if not box:
        return None, None, True
    kind, payload = box[0]
    if kind == "rc":
        return payload, None, False
    return None, payload, False


def _classified(exc: BaseException, armed: Dict[str, str]) -> bool:
    """The invariant's "classified error naming the site" test: the
    failure must be one of the contract's error shapes AND traceable —
    its message names an armed site, carries an injected-failpoint
    marker, cites the artifact-validation contract (manifest rejection
    of a torn artifact names the FILE, not the write site), or
    classifies transient under retry.classify.  A stray InputError
    with none of those markers is a real regression and must count as
    an UNCLASSIFIED crash, not ride the invariant."""
    from fastapriori_tpu.errors import InputError
    from fastapriori_tpu.reliability import failpoints, retry

    if isinstance(exc, failpoints.InjectedAbort):
        return True
    msg = str(exc)
    named = any(site in msg for site in armed) or (
        "injected failpoint" in msg
    )
    contract = any(
        w in msg for w in ("truncated", "corrupt", "manifest", "checkpoint")
    )
    if isinstance(exc, (InputError, FileNotFoundError, OSError)):
        return named or contract or retry.classify(exc) == "transient"
    if isinstance(exc, RuntimeError):
        return retry.classify(exc) == "transient" or named
    return False


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def _validate_artifacts(out: str) -> Optional[str]:
    """Manifest cross-validation of every committed artifact under
    ``out``: returns the name of the first artifact the manifest
    REJECTS (the truncation-detected case), None when all validate."""
    from fastapriori_tpu.errors import InputError
    from fastapriori_tpu.io import resume as resume_io

    try:
        manifest = resume_io.load_manifest(out)
    except (InputError, FileNotFoundError):
        return "MANIFEST.json"
    for name in manifest:
        try:
            resume_io.validate_artifact_bytes(
                out, name, _read(out + name), manifest
            )
        except (InputError, FileNotFoundError):
            return name
    return None


def run_scenario(
    schedule: dict, inp: str, root: str, clean: Dict[str, bytes],
    timeout_s: float,
) -> Outcome:
    """One scenario under the invariant (module docstring)."""
    from fastapriori_tpu.io.checkpoint import (
        checkpoint_available,
        validate_checkpoint,
    )
    from fastapriori_tpu.reliability import failpoints, ledger

    from fastapriori_tpu.obs import flight

    out = os.path.join(root, f"s{schedule['seed']}") + os.sep
    os.makedirs(out)

    def fail(detail: str) -> Outcome:
        """Every FAIL ships its own post-mortem (ISSUE 11): the flight
        recorder's ring — the ledger/span/watchdog events leading up to
        the failure — dumps manifest-committed next to the scenario's
        artifacts (the harness keeps the workdir on failure)."""
        try:
            path = flight.dump(out, f"chaos: {detail}"[:400])
            print(f"chaos: flight recorder dumped: {path}")
        # lint: waive G006 G009 -- best-effort post-mortem on an already-failed scenario
        except Exception:
            pass
        return Outcome("FAIL", detail)
    argv = [
        inp, out, "--min-support", "0.08",
        "--engine", schedule["engine"],
    ]
    if schedule["checkpoint"]:
        argv += [
            "--checkpoint-every-level",
            "--checkpoint-cadence", str(schedule["cadence"]),
        ]
    ledger.reset()
    failpoints.disarm_all()
    for site, spec in schedule["failpoints"].items():
        failpoints.arm(site, spec)
    try:
        rc, exc, hung = _run_cli_bounded(argv, timeout_s)
    finally:
        failpoints.disarm_all()
    armed = schedule["failpoints"]
    degraded = ledger.summary()
    if hung:
        return fail(
            f"hang: no result within {timeout_s}s under {armed}"
        )
    truncated = any("truncate" in s for s in armed.values())
    if exc is not None:
        if not _classified(exc, armed):
            return fail(
                f"unclassified crash {type(exc).__name__}: {exc} "
                f"under {armed}"
            )
        if isinstance(exc, failpoints.InjectedAbort) and (
            schedule["checkpoint"] and checkpoint_available(out)
        ):
            # The kill contract: a structurally valid checkpoint that
            # resumes to byte-identical output.  A schedule may ALSO
            # have armed a truncation against the checkpoint write —
            # then validation REJECTING the torn file is exactly the
            # manifest contract (invariant case 2), while a rejected
            # checkpoint with no truncation armed is real corruption.
            from fastapriori_tpu.errors import InputError
            try:
                validate_checkpoint(out)
            except InputError as verr:
                if any(
                    site.startswith("write.checkpoint")
                    and "truncate" in spec
                    for site, spec in armed.items()
                ):
                    return Outcome(
                        "classified",
                        f"torn checkpoint rejected: {verr}",
                    )
                return fail(
                    f"corrupt checkpoint with no truncation armed: "
                    f"{verr} under {armed}"
                )
            rc2, exc2, hung2 = _run_cli_bounded(
                [inp, out, "--min-support", "0.08",
                 "--resume-from", out],
                timeout_s,
            )
            if hung2 or exc2 is not None or rc2 != 0:
                return fail(
                    f"resume after kill failed (rc={rc2}, exc={exc2}) "
                    f"under {armed}"
                )
            for name, want in clean.items():
                if _read(out + name) != want:
                    return fail(
                        f"resumed {name} differs from clean run "
                        f"under {armed}"
                    )
            return Outcome("killed_resumed", str(armed))
        return Outcome("classified", f"{type(exc).__name__} under {armed}")
    if rc == 2:
        return Outcome("classified", f"exit 2 under {armed}")
    if rc != 0:
        return fail(f"unexpected exit code {rc} under {armed}")
    for name, want in clean.items():
        if _read(out + name) == want:
            continue
        if truncated and _validate_artifacts(out) is not None:
            # Not silent: the manifest rejects the torn artifact, which
            # is the truncation contract (io/writer.py).
            return Outcome("classified", f"truncation detected ({name})")
        return fail(
            f"SILENT CORRUPTION: {name} differs (rc 0, "
            f"degraded={degraded}) under {armed}"
        )
    kind = "degraded" if degraded.get("cascade") else "identical"
    return Outcome(kind, f"degraded={degraded} under {armed}")


# ---------------------------------------------------------------------------
# multi-process fault domain soak (ISSUE 12): real subprocess meshes
# coordinated through the file-transport quorum (reliability/quorum.py)
# — the simulated-multiprocess harness made real with actual processes,
# because the pinned jax 0.4.37 CPU backend refuses multiprocess
# computations (the real 2-process transport version-gates with
# tests/test_distributed.py).  The invariant EXTENDS the single-process
# one: all surviving ranks agree byte-identically, or all failing ranks
# fail classified naming a rank/site; never a hang, never a mixed-epoch
# artifact.

MP_KINDS = (
    "kill",
    "divergence",
    "flap",
    "hb_delay",
    "wstotals",
    # Elastic-mesh continuation (ISSUE 17): the same deaths as "kill",
    # but with FA_EPOCH_RETRY_MAX armed so survivors must ABSORB the
    # loss — abort the in-flight level, re-rendezvous under a bumped
    # mesh epoch, and finish byte-identical to the clean run — plus
    # the exhaustion arm where deaths past the budget must still end
    # classified on every rank.
    "elastic_kill",
    "elastic_rendezvous",
    "elastic_exhaust",
    # Serving-mesh host kill (ISSUE 19): subprocess serving hosts
    # behind the request router; one dies abruptly mid-burst.  The
    # survivors must keep serving CORRECT responses, the dead host's
    # in-flight share must drain as recorded sheds (exact accounting),
    # and the loss must land on the ledger (serve_mesh full->degraded
    # + serve_host_lost) — never a hang, never a silent wrong answer.
    "serve_kill",
)

# Divergence injections: a transient-exhaustion spec that walks ONE
# consensus chain on the target rank only (oom*3 exhausts the default
# 3-attempt retry budget and the engine layer steps its chain).  Each
# entry pins the engine AND checkpointing the schedule must force so
# the armed site is actually on the target's path: the whole-loop
# fused program (fetch.fused) only runs WITHOUT a checkpoint prefix,
# the segment fold (fetch.tail) only WITH one.  The pair-sparse entry
# (ISSUE 15) exhausts the sparse PAIR fetch on the 8-device mesh,
# walking the target down exchange hier→flat AND count_reduce
# sparse→dense mid-mine — peers must adopt both at their next level
# boundary or their two-level collectives would never match the
# target's flat/dense ones.
_DIVERGENCE_MENU: Tuple[Tuple[str, str, bool], ...] = (
    ("fetch.fused:oom*3", "fused", False),
    ("fetch.tail:oom*3", "fused", True),  # segment fold under ckpt
    ("fetch.pair_sparse:oom*3", "level", True),  # ISSUE 15
)


def make_mp_schedule(seed: int, procs: int) -> dict:
    """ONE deterministic multi-process scenario from ``seed``: the
    fault kind, the target rank, the per-rank failpoint spec, and the
    pipeline shape.  Pure function of (seed, procs) — tests pin
    same-seed equality, like :func:`make_schedule`."""
    rng = random.Random(seed)
    kind = rng.choice(MP_KINDS)
    target = rng.randrange(procs)
    engine = rng.choice(("auto", "level", "fused"))
    # Fenced commits exercised by default; a divergence entry may turn
    # checkpointing off when its armed site needs the whole-loop path.
    checkpoint = True
    failpoints_by_rank: Dict[int, str] = {}
    # Elastic retry budget (ISSUE 17): 0 keeps continuation disabled —
    # the non-elastic kinds must behave exactly as before.
    epoch_retry_max = 0
    if kind == "kill":
        # Sites: a committed level boundary, or the mine.start W_s
        # rendezvous itself (ISSUE 15) — a rank dying INSIDE the
        # weight-total exchange must surface on every peer as a
        # classified PeerLost naming it, never a rendezvous hang.
        site = rng.choice(
            ("level.2", "level.3", "quorum.mine.wstotals")
        )
        failpoints_by_rank[target] = f"{site}:abort"
    elif kind == "divergence":
        spec, engine, checkpoint = rng.choice(_DIVERGENCE_MENU)
        failpoints_by_rank[target] = spec
    elif kind == "flap":
        # Coordinator flap: rank 0 stalls at a level boundary for
        # longer than several heartbeat intervals but well under the
        # quorum timeout — a SLOW coordinator must not be declared
        # dead (the background heartbeat keeps beating through the
        # stall) and the run must complete identically.
        target = 0
        failpoints_by_rank[0] = f"level.2:delay@{rng.randint(800, 1500)}"
    elif kind == "hb_delay":
        # Heartbeat jitter on the target: each beat sleeps; liveness
        # judgment must tolerate it (interval << timeout), so the run
        # completes identically — a laggy heartbeat is not a death.
        failpoints_by_rank[target] = (
            f"quorum.heartbeat:delay@{rng.randint(100, 300)}"
        )
    elif kind == "wstotals":  # ISSUE 15
        # A slow rank INSIDE the W_s rendezvous: the delay is well
        # under the quorum timeout, so peers must wait it out (the
        # heartbeat keeps beating through it) and the run completes
        # identically — a laggy exchange is not a death.
        failpoints_by_rank[target] = (
            f"quorum.mine.wstotals:delay@{rng.randint(500, 1500)}"
        )
    elif kind == "elastic_kill":
        # Kill at a committed level boundary with continuation armed:
        # survivors must abort the in-flight level, re-rendezvous
        # under mesh epoch 1, and finish byte-identical to the clean
        # run (membership never changes mined bytes on full replicas).
        # level.3 commits per-level only under the LEVEL engine — the
        # segment engine's cadence can fold past it, leaving the
        # failpoint unreached (the armed rank would exit 0) — so the
        # fused/auto draws pin the always-committed level.2 boundary.
        site = rng.choice(("level.2", "level.3"))
        if engine != "level":
            site = "level.2"
        failpoints_by_rank[target] = f"{site}:abort"
        epoch_retry_max = rng.choice((1, 2))
    elif kind == "elastic_rendezvous":
        # Kill INSIDE the mine.start W_s rendezvous itself: the abort
        # lands mid-exchange, so the epoch-namespaced quorum rounds
        # must keep the survivors' post-abort re-exchange from ever
        # pairing with the dead rank's pre-abort payload.
        failpoints_by_rank[target] = "quorum.mine.wstotals:abort"
        epoch_retry_max = 1
    elif kind == "serve_kill":
        # Router-side kill (ISSUE 19): the fault is ProcHost.kill() in
        # the serving scenario runner, not a mining failpoint — the
        # engine/checkpoint/failpoint fields stay at their defaults and
        # are ignored by run_serve_mesh_scenario.
        pass
    else:  # elastic_exhaust (ISSUE 17)
        # Deaths past the budget must still END classified.  With
        # >= 3 ranks a double kill either coalesces into one absorbed
        # transition (survivors continue, byte-identical) or sequences
        # past the budget (every survivor exits classified) — both
        # legal, neither a hang.  With 2 ranks the zero budget makes
        # exhaustion-at-first-death deterministic.  The LEVEL engine is
        # pinned so both armed level boundaries commit (and fire)
        # regardless of cadence — budget semantics are what this kind
        # covers, and they are engine-independent.
        engine = "level"
        if procs >= 3:
            failpoints_by_rank[target] = "level.2:abort"
            failpoints_by_rank[(target + 1) % procs] = "level.3:abort"
            epoch_retry_max = 1
        else:
            failpoints_by_rank[target] = "level.2:abort"
            epoch_retry_max = 0
    sched = {
        "seed": seed,
        "kind": kind,
        "procs": procs,
        "target": target,
        "engine": engine,
        "checkpoint": checkpoint,
        "cadence": rng.choice((1, 2)),
        "failpoints_by_rank": failpoints_by_rank,
        "epoch_retry_max": epoch_retry_max,
    }
    if kind == "serve_kill":
        # Router-side kill (ProcHost.kill), not a mining failpoint:
        # the burst index the target host dies at, and the burst size.
        sched["kill_at"] = rng.randint(40, 120)
        sched["n_requests"] = 300
    return sched


def _spawn_rank(
    schedule: dict, inp: str, out_r: str, qdir: str, rank: int,
    log_path: str,
) -> "subprocess.Popen":
    import subprocess

    argv = [
        sys.executable, "-m", "fastapriori_tpu",
        inp, out_r, "--min-support", "0.08",
        "--engine", schedule["engine"], "--platform", "cpu",
    ]
    if schedule["checkpoint"]:
        argv += [
            "--checkpoint-every-level",
            "--checkpoint-cadence", str(schedule["cadence"]),
        ]
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        FA_NO_COMPILE_LOG="1",
        FA_QUORUM_DIR=qdir,
        FA_QUORUM_RANK=str(rank),
        FA_QUORUM_PROCS=str(schedule["procs"]),
        # Bounded everything: worst-case stall on a dead peer is
        # 3 attempts x 20 s, inside the scenario timeout.
        FA_QUORUM_TIMEOUT_S="20",
        FA_HEARTBEAT_MS="100",
    )
    env.pop("FA_FAILPOINTS", None)
    # Elastic retry budget (ISSUE 17): uniform across ranks — every
    # survivor must reach the same exhaustion verdict independently.
    env.pop("FA_EPOCH_RETRY_MAX", None)
    retry_max = int(schedule.get("epoch_retry_max", 0))
    if retry_max:
        env["FA_EPOCH_RETRY_MAX"] = str(retry_max)
    spec = schedule["failpoints_by_rank"].get(rank)
    if spec is not None:
        env["FA_FAILPOINTS"] = spec  # schedule specs ARE the env format
    # lint: waive G009 -- per-rank stderr capture in a fresh temp dir, not a run artifact
    log = open(log_path, "wb")
    return subprocess.Popen(
        argv, cwd=_REPO_ROOT, env=env, stdout=log, stderr=log
    )


def _checkpoint_epoch_consistent(
    prefix: str, qdir: str
) -> Optional[str]:
    """The no-mixed-epoch-artifact assertion: a committed checkpoint's
    manifest fence must match its meta fence and must not exceed the
    domain's authoritative FENCE.  Returns a problem string or None."""
    from fastapriori_tpu.io.checkpoint import CHECKPOINT_NAME
    from fastapriori_tpu.io.resume import manifest_fence

    if not os.path.exists(prefix + CHECKPOINT_NAME):
        return None
    m_fence = manifest_fence(prefix)
    try:
        with open(os.path.join(qdir, "FENCE")) as f:
            dom_fence = int(json.load(f)["fence"])
    except (OSError, ValueError, KeyError):
        dom_fence = 0
    import io as _io

    import numpy as _np

    with open(prefix + CHECKPOINT_NAME, "rb") as f:
        try:
            with _np.load(_io.BytesIO(f.read())) as z:
                meta = z["meta"]
                meta_fence = int(meta[4]) if meta.shape[0] >= 5 else 0
        # lint: waive G006 -- a torn checkpoint is the MANIFEST contract's verdict, not this epoch check's
        except Exception:
            return None
    if m_fence is not None and meta_fence and m_fence != meta_fence:
        return (
            f"mixed-epoch checkpoint under {prefix}: manifest fence "
            f"{m_fence} != meta fence {meta_fence}"
        )
    if dom_fence and meta_fence > dom_fence:
        return (
            f"checkpoint fence {meta_fence} exceeds the domain FENCE "
            f"{dom_fence} under {prefix}"
        )
    return None


# Markers must be PRECISE contract phrases, never loose substrings: a
# checkpoint-enabled crash's traceback contains frame names like
# "io/checkpoint.py", so a bare "checkpoint" marker would read a
# genuinely unclassified crash in the fence code as classified and the
# soak would pass exactly where it must FAIL.
_CLASSIFIED_MARKERS = (
    "injected failpoint",  # InjectedAbort / injected transient
    "quorum peer rank",  # PeerLost naming the rank
    "mesh divergence",  # MeshDivergence naming both sides
    "stale checkpoint",  # StaleFenceError (split-brain commit/resume)
    "mesh epoch",  # elastic fence-out / superseded straggler (ISSUE 17)
    "corrupt checkpoint",  # structural rejection
    "fails manifest validation",  # torn-artifact contract
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "error: ",  # the CLI's classified one-liners (rc 2/3); raw
    # tracebacks print "SomeError:" — capital E — and never match
)


def run_serve_mesh_scenario(
    schedule: dict, inp: str, root: str, timeout_s: float
) -> Outcome:
    """Kill-a-serving-host-mid-burst on a real subprocess mesh
    (ISSUE 19): ``procs`` ProcHost workers behind the router; the
    target dies abruptly at ``kill_at``.  Invariants: every request
    completes (never a hang), shed accounting is exact (one answer per
    request — served or recorded shed, never both or neither),
    survivors' responses stay byte-identical to the single-host
    baseline, and the loss lands on the ledger (serve_mesh
    full->degraded cascade + serve_host_lost)."""
    from fastapriori_tpu.config import MinerConfig
    from fastapriori_tpu.io.reader import tokenize_line
    from fastapriori_tpu.reliability import ledger
    from fastapriori_tpu.serve import MeshRouter, ProcHost, ServingState

    procs = schedule["procs"]
    target = schedule["target"] % procs
    tag = f"serve{schedule['seed']}x{procs}"
    base = os.path.join(root, tag)
    os.makedirs(base, exist_ok=True)
    detail = (
        f"kind=serve_kill target=w{target} hosts={procs} "
        f"kill_at={schedule['kill_at']}"
    )
    cfg = MinerConfig(min_support=0.08, retain_csr=False)
    state = ServingState.from_mine(inp + "D.dat", config=cfg)
    ckpt = os.path.join(base, "ckpt_")
    state.save(ckpt)
    with open(inp + "D.dat") as f:
        pool = [tokenize_line(l) for l in f][:40]
    baseline = state.recommend_batch(pool)
    ledger.reset()
    hosts = [
        ProcHost(
            f"w{i}", os.path.join(base, f"w{i}"), ckpt,
            queue_depth=1024, env={"JAX_PLATFORMS": "cpu"},
        )
        for i in range(procs)
    ]
    mesh = MeshRouter(hosts)
    n_req = schedule["n_requests"]
    reqs = []
    try:
        for i in range(n_req):
            reqs.append(mesh.submit(pool[i % len(pool)]))
            if i == schedule["kill_at"]:
                hosts[target].kill()
        done = mesh.wait_for(reqs, timeout_s=max(timeout_s - 10.0, 10.0))
        served = sum(1 for r in reqs if not r.shed)
        shed = n_req - served
        st = mesh.stats()
    finally:
        mesh.stop()
    if not done or not all(r.done for r in reqs):
        pending = sum(1 for r in reqs if not r.done)
        return Outcome(
            "FAIL", f"hang: {pending} requests never answered — {detail}"
        )
    if st["hosts_lost"] != 1:
        return Outcome(
            "FAIL", f"hosts_lost {st['hosts_lost']} != 1 — {detail}"
        )
    # Mesh counters at an abrupt kill are inherently a snapshot race
    # (the dead host's stats.json freezes at its last publish), so the
    # request-side ledger above is the accounting truth here; the
    # no-tolerance exact-accounting pin lives in
    # tests/test_serve_router.py on LocalHost, where nothing lags.
    if st["shed"] < st["lost_shed"]:
        return Outcome(
            "FAIL",
            f"lost sheds not folded into the shed total "
            f"({st['lost_shed']} > {st['shed']}) — {detail}",
        )
    wrong = sum(
        1
        for i, r in enumerate(reqs)
        if not r.shed and r.item != baseline[i % len(pool)]
    )
    if wrong:
        return Outcome(
            "FAIL", f"{wrong} wrong survivor responses — {detail}"
        )
    events = ledger.snapshot()
    cascade = [
        e for e in events
        if e.get("kind") == "cascade" and e.get("chain") == "serve_mesh"
    ]
    lost = [e for e in events if e.get("kind") == "serve_host_lost"]
    if not cascade or not lost:
        return Outcome(
            "FAIL",
            f"host loss unrecorded (cascade={len(cascade)} "
            f"serve_host_lost={len(lost)}) — {detail}",
        )
    return Outcome(
        "degraded",
        f"{detail} served={served} shed={shed} "
        f"lost_shed={st['lost_shed']}",
    )


def run_mp_scenario(
    schedule: dict, inp: str, root: str, clean: Dict[str, bytes],
    timeout_s: float,
) -> Outcome:
    """One multi-process scenario under the extended invariant."""
    import subprocess

    if schedule["kind"] == "serve_kill":
        return run_serve_mesh_scenario(schedule, inp, root, timeout_s)
    procs = schedule["procs"]
    tag = f"mp{schedule['seed']}x{procs}"
    qdir = os.path.join(root, tag + ".q")
    outs = [
        os.path.join(root, tag, f"r{r}") + os.sep for r in range(procs)
    ]
    logs = [os.path.join(root, tag, f"r{r}.log") for r in range(procs)]
    for o in outs:
        os.makedirs(o)
    children = [
        _spawn_rank(schedule, inp, outs[r], qdir, r, logs[r])
        for r in range(procs)
    ]
    t0 = time.monotonic()
    hung = False
    while any(c.poll() is None for c in children):
        if time.monotonic() - t0 > timeout_s:
            hung = True
            for c in children:
                if c.poll() is None:
                    c.kill()
            break
        time.sleep(0.1)
    for c in children:
        try:
            c.wait(timeout=10)
        except subprocess.TimeoutExpired:
            hung = True
    rcs = [c.returncode for c in children]
    texts = []
    for p in logs:
        try:
            with open(p, "rb") as f:
                texts.append(f.read().decode("utf-8", "replace"))
        except OSError:
            texts.append("")
    detail = f"kind={schedule['kind']} target={schedule['target']} " \
             f"engine={schedule['engine']} rcs={rcs}"
    if hung:
        return Outcome("FAIL", f"hang: {detail} (no exit in {timeout_s}s)")
    # Mixed-epoch artifact check on every rank's committed checkpoint.
    for o in outs:
        problem = _checkpoint_epoch_consistent(o, qdir)
        if problem:
            return Outcome("FAIL", f"{problem} ({detail})")
    target = schedule["target"]
    failed = [r for r in range(procs) if rcs[r] != 0]
    for r in failed:
        if not any(m in texts[r] for m in _CLASSIFIED_MARKERS):
            return Outcome(
                "FAIL",
                f"rank {r} failed UNCLASSIFIED (rc={rcs[r]}) — "
                f"{detail}; tail: {texts[r][-300:]!r}",
            )
    survivors = [r for r in range(procs) if rcs[r] == 0]
    if len(survivors) >= 2 or (survivors and not failed):
        base = None
        for r in survivors:
            blob = tuple(
                _read(outs[r] + n) for n in ("freqItemset", "recommends")
            )
            if base is None:
                base = blob
            elif blob != base:
                return Outcome(
                    "FAIL",
                    f"survivor outputs DIVERGE (rank {survivors[0]} vs "
                    f"{r}) — {detail}",
                )
        if not failed and base is not None:
            want = tuple(clean[n] for n in ("freqItemset", "recommends"))
            if base != want:
                return Outcome(
                    "FAIL",
                    f"survivor outputs differ from the clean run — "
                    f"{detail}",
                )
    if schedule["kind"] == "divergence" and not failed:
        # The lockstep assertion: the target walked its chain locally
        # (cascade on its ledger warn-stream) AND at least one peer
        # ADOPTED it through the consensus exchange — without the
        # exchange the peers would never print quorum_adopt and a real
        # mesh would have hung at the next collective.
        if "cascade" not in texts[target]:
            return Outcome(
                "FAIL",
                f"divergence target never walked its chain — {detail}",
            )
        peers = [r for r in range(procs) if r != target]
        if not any("quorum_adopt" in texts[r] for r in peers):
            return Outcome(
                "FAIL",
                f"no peer adopted the target's degradation (consensus "
                f"exchange silent) — {detail}",
            )
        return Outcome("degraded", detail)
    if schedule["kind"] in ("elastic_kill", "elastic_rendezvous"):
        # The elastic continuation invariant (ISSUE 17): the killed
        # rank dies classified (checked above), every survivor ABSORBS
        # the death — abort, re-rendezvous under the bumped mesh
        # epoch, finish — and the survivor output is byte-identical to
        # the CLEAN run, because membership never changes mined bytes
        # on full replicas.
        if rcs[target] == 0:
            return Outcome("FAIL", f"killed rank exited 0 — {detail}")
        alive = [r for r in range(procs) if r != target]
        bad = [r for r in alive if rcs[r] != 0]
        if bad:
            return Outcome(
                "FAIL",
                f"survivor rank(s) {bad} failed under elastic "
                f"continuation (FA_EPOCH_RETRY_MAX="
                f"{schedule['epoch_retry_max']}) — {detail}; tail: "
                f"{texts[bad[0]][-300:]!r}",
            )
        want = tuple(clean[n] for n in ("freqItemset", "recommends"))
        for r in alive:
            blob = tuple(
                _read(outs[r] + n) for n in ("freqItemset", "recommends")
            )
            if blob != want:
                return Outcome(
                    "FAIL",
                    f"survivor rank {r} output differs from the clean "
                    f"run after elastic continuation — {detail}",
                )
        if not any("mesh_epoch" in texts[r] for r in alive):
            return Outcome(
                "FAIL",
                f"no survivor recorded a mesh_epoch transition — "
                f"elastic continuation never engaged — {detail}",
            )
        return Outcome("elastic", detail)
    if schedule["kind"] == "elastic_exhaust":
        # Deaths past the retry budget: survivors either ALL absorbed
        # the coalesced loss (continue, byte-identical to clean) or
        # ALL exited classified at exhaustion — never a hang (checked
        # above), never an unclassified crash (checked above), never a
        # split verdict (completed rejoins leave every survivor with
        # the same mesh epoch, so the budget check is symmetric).
        died = sorted(
            r for r in schedule["failpoints_by_rank"] if rcs[r] != 0
        )
        if not died:
            return Outcome("FAIL", f"no armed rank died — {detail}")
        alive = [r for r in range(procs) if r not in died]
        if all(rcs[r] == 0 for r in alive):
            want = tuple(clean[n] for n in ("freqItemset", "recommends"))
            for r in alive:
                blob = tuple(
                    _read(outs[r] + n)
                    for n in ("freqItemset", "recommends")
                )
                if blob != want:
                    return Outcome(
                        "FAIL",
                        f"survivor rank {r} output differs from the "
                        f"clean run after absorbed deaths — {detail}",
                    )
            return Outcome("elastic", f"{detail} absorbed")
        if any(rcs[r] == 0 for r in alive):
            return Outcome(
                "FAIL",
                f"survivors SPLIT at exhaustion (some continued, some "
                f"classified) — {detail}",
            )
        return Outcome("classified", f"{detail} exhausted")
    if schedule["kind"] == "kill":
        if rcs[target] == 0:
            return Outcome(
                "FAIL", f"killed rank exited 0 — {detail}"
            )
        # Survivors either finished before needing the dead peer
        # (impossible past the mine.end rendezvous, but allowed by the
        # invariant) or failed classified naming the rank — both
        # checked above.  A survivor that names the dead rank proves
        # bounded peer-death detection.
        named = any(
            f"rank {target}" in texts[r]
            for r in range(procs)
            if r != target and rcs[r] != 0
        )
        return Outcome(
            "classified",
            f"{detail} peer_named={named}",
        )
    if failed:
        return Outcome("classified", detail)
    return Outcome("identical", detail)


def main_chaos_mp(args, seeds: List[int]) -> int:
    """The multi-process soak driver (``--procs N``): seeded schedules
    over kill/divergence/flap/heartbeat-delay/elastic scenarios, each
    a real N-subprocess mesh over the file-transport quorum."""
    t0 = time.monotonic()
    root = tempfile.mkdtemp(prefix="fa_chaos_mp_")
    failures: List[str] = []
    tallies: Dict[str, int] = {}
    ran = dropped = 0
    try:
        inp = make_inputs(root)
        out_clean = os.path.join(root, "clean") + os.sep
        os.makedirs(out_clean)
        from fastapriori_tpu.cli import main as cli_main

        if cli_main([inp, out_clean, "--min-support", "0.08"]) != 0:
            print("chaos-mp: FAIL: clean run failed", file=sys.stderr)
            return 1
        clean = {
            n: _read(out_clean + n)
            for n in ("freqItemset", "recommends")
        }
        print(
            f"chaos-mp: {args.procs} processes, seeds {seeds} x "
            f"{args.scenarios}",
        )
        for seed in seeds:
            for i in range(args.scenarios):
                if time.monotonic() - t0 > args.budget_s:
                    dropped += 1
                    continue
                schedule = make_mp_schedule(seed * 100 + i, args.procs)
                outcome = run_mp_scenario(
                    schedule, inp, root, clean, args.scenario_timeout_s
                )
                ran += 1
                tallies[outcome.kind] = tallies.get(outcome.kind, 0) + 1
                ok = "FAIL" if outcome.kind == "FAIL" else "ok"
                print(
                    f"chaos-mp[{schedule['seed']}] {ok} "
                    f"{outcome.kind}: {outcome.detail}"
                )
                if outcome.kind == "FAIL":
                    failures.append(outcome.detail)
    finally:
        if not args.keep and not failures:
            shutil.rmtree(root, ignore_errors=True)
        else:
            # The per-rank logs and rank-suffixed flight dumps are the
            # post-mortem; tools/flight_merge.py reassembles them.
            print(f"chaos-mp: workdirs kept under {root}")
    wall = time.monotonic() - t0
    if dropped:
        print(
            f"chaos-mp: {dropped} scenario(s) dropped past the "
            f"{args.budget_s}s budget — coverage was NOT complete",
            file=sys.stderr,
        )
    print(
        f"chaos-mp: {'FAIL' if failures else 'OK'} scenarios={ran} "
        f"{tallies} wall={wall:.1f}s (budget {args.budget_s}s)"
    )
    return 1 if failures else 0


def main_chaos(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--seeds", default="0,1,2,3",
        help="comma-separated base seeds (offset by FA_CHAOS_SEED)",
    )
    ap.add_argument(
        "--scenarios", type=int, default=2,
        help="scenarios per seed (seed*100+i derives each schedule)",
    )
    ap.add_argument(
        "--budget-s", type=float, default=150.0,
        help="soft wall budget: no new scenario starts past it "
        "(dropped scenarios are LOGGED, never silently skipped)",
    )
    ap.add_argument(
        "--scenario-timeout-s", type=float, default=90.0,
        help="per-scenario hang bound (the no-hang invariant)",
    )
    ap.add_argument("--keep", action="store_true", help="keep workdirs")
    ap.add_argument(
        "--procs", type=int, default=1,
        help="multi-process soak (ISSUE 12): spawn this many real "
        "subprocess ranks per scenario, coordinated through the "
        "file-transport quorum (reliability/quorum.py); schedules "
        "cover kill-mid-level / divergence injection / coordinator "
        "flap / heartbeat delay / elastic-mesh continuation and "
        "exhaustion (default 1 = the single-process soak)",
    )
    args = ap.parse_args(argv)

    # 8 virtual CPU devices BEFORE any backend init, so the sharded
    # paths (sparse exchange, vertical lanes, sharded rules) are real
    # multi-device programs in the soak — the conftest mesh, standalone
    # (XLA_FLAGS works on every pinned jax; jax_num_cpu_devices only on
    # newer ones).  Compile-log lines off: the soak's stdout is its
    # per-scenario verdict stream.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        )
    os.environ.setdefault("FA_NO_COMPILE_LOG", "1")
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except (AttributeError, RuntimeError):  # old jax / already init
        pass
    from fastapriori_tpu.cli import main as cli_main

    base = _base_seed()
    seeds = [int(s) + base for s in args.seeds.split(",") if s.strip()]
    if args.procs > 1:
        return main_chaos_mp(args, seeds)
    t0 = time.monotonic()
    root = tempfile.mkdtemp(prefix="fa_chaos_")
    failures: List[str] = []
    tallies: Dict[str, int] = {}
    ran = dropped = 0
    try:
        inp = make_inputs(root)
        out_clean = os.path.join(root, "clean") + os.sep
        os.makedirs(out_clean)
        if cli_main([inp, out_clean, "--min-support", "0.08"]) != 0:
            print("chaos: FAIL: clean run failed", file=sys.stderr)
            return 1
        clean = {
            n: _read(out_clean + n)
            for n in ("freqItemset", "recommends")
        }
        sites = enrolled_sites()
        print(
            f"chaos: {len(sites)} enrolled sites "
            f"({len(fetch_sites_from_inventory())} from the fetch "
            f"census), seeds {seeds} x {args.scenarios}",
        )
        tainted = False
        for seed in seeds:
            for i in range(args.scenarios):
                if tainted or time.monotonic() - t0 > args.budget_s:
                    dropped += 1
                    continue
                schedule = make_schedule(seed * 100 + i, sites)
                outcome = run_scenario(
                    schedule, inp, root, clean, args.scenario_timeout_s
                )
                ran += 1
                tallies[outcome.kind] = tallies.get(outcome.kind, 0) + 1
                tag = "FAIL" if outcome.kind == "FAIL" else "ok"
                print(
                    f"chaos[{schedule['seed']}] {tag} "
                    f"{outcome.kind}: {outcome.detail}"
                )
                if outcome.kind == "FAIL":
                    failures.append(outcome.detail)
                    if outcome.detail.startswith("hang"):
                        # The hung scenario's daemonized CLI thread is
                        # still running and shares the process-global
                        # ledger/failpoint registries — later scenarios
                        # would no longer be deterministic functions of
                        # their seed.  The soak is already failed; stop
                        # scheduling rather than report tainted verdicts.
                        tainted = True
                        print(
                            "chaos: process state tainted by the hung "
                            "scenario — remaining scenarios skipped",
                            file=sys.stderr,
                        )
    finally:
        # A failed soak keeps its workdirs regardless of --keep: the
        # FAIL scenarios' flight-recorder dumps (<out>flight.json — the
        # post-mortem, ISSUE 11) live there, and deleting the evidence
        # of the failure the soak exists to catch would be absurd.
        if not args.keep and not failures:
            shutil.rmtree(root, ignore_errors=True)
        else:
            print(f"chaos: workdirs kept under {root}")
    wall = time.monotonic() - t0
    if dropped:
        print(
            f"chaos: {dropped} scenario(s) dropped (the "
            f"{args.budget_s}s budget, or taint after a hang) — "
            "coverage was NOT complete",
            file=sys.stderr,
        )
    print(
        f"chaos: {'FAIL' if failures else 'OK'} scenarios={ran} "
        f"{tallies} wall={wall:.1f}s (budget {args.budget_s}s)"
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main_chaos())
