"""Seeded chaos soak harness (ISSUE 9): deterministic randomized
failpoint schedules against the full mine+rules+recommend pipeline.

The point tests (tests/test_reliability.py, tools/failpoint_smoke.py)
each arm ONE hand-picked site; with 20+ audited fetch sites and a
five-deep engine-fallback stack the *interaction* space is far larger
than they cover.  This harness derives schedules from a seeded RNG over
``sites × kinds × counts`` — the site list comes from the lint-censused
contract inventory (``tools/lint/inventory.json``), so every NEW fetch
site is auto-enrolled the moment the census regenerates — and runs the
real CLI pipeline under each, asserting the global invariant:

    Every scenario ends in exactly one of:
      1. **byte-identical output** to the clean run (degradations
         allowed — they are counted from the ledger);
      2. a **classified error naming the site** (InputError exit 2, a
         transient/injected status error, or the InjectedAbort kill
         stand-in — after which a checkpointed run must resume
         byte-identically and a truncated artifact must be REJECTED by
         manifest validation);
      3. never anything else: a hang (per-scenario wall bound enforced
         from a watchdog thread), silent corruption (different bytes
         with rc 0 and no truncation armed), or an unclassified crash
         (any exception outside the classification contract).

Same seed → same schedule → same outcome (asserted by
tests/test_reliability.py); the CI soak (`make chaos`, tools/ci.sh)
runs a fixed seed set under a wall budget and logs its wall time like
lint's 10 s budget.

Usage::

    python tools/chaos.py [--seeds 0,1,2,3] [--scenarios 4]
                          [--budget-s 120] [--scenario-timeout-s 90]

``FA_CHAOS_SEED`` (strict int) offsets the whole seed set — the knob
for soaking a different schedule region without editing the CI set.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # `python tools/chaos.py`
    sys.path.insert(0, _REPO_ROOT)

_INVENTORY = os.path.join(_REPO_ROOT, "tools", "lint", "inventory.json")

# Non-fetch sites worth soaking that the fetch census cannot enroll:
# artifact writes (truncate/io), the post-commit level hooks (the
# kill-and-resume kill points), the mid-mine drain, and the native
# loader.  Kept small and explicit — fetch sites auto-enroll.
EXTRA_SITES: Dict[str, Tuple[str, ...]] = {
    "write.freqItemset": ("truncate", "io", "delay"),
    "write.freqItems": ("truncate", "delay"),
    "write.checkpoint.npz": ("truncate", "io", "delay"),
    "level.2": ("abort", "delay"),
    "level.3": ("abort", "delay"),
    "level.4": ("abort",),
    "drain.counts": ("oom", "delay"),
    "native.load": ("io",),
    "rules.upload": ("oom", "delay"),
}

_FETCH_KINDS = ("oom*1", "oom*2", "oom", "io", "delay")


def fetch_sites_from_inventory(path: str = _INVENTORY) -> List[str]:
    """``fetch.<label>`` for every censused fetch site — the
    auto-enrollment contract: a new audited fetch site enters the soak
    the moment ``--write-inventory`` regenerates the census."""
    with open(path) as fh:
        inv = json.load(fh)
    return sorted(
        "fetch." + e["label"] for e in inv.get("fetch_sites", [])
    )


def enrolled_sites(path: str = _INVENTORY) -> Dict[str, Tuple[str, ...]]:
    """site -> candidate kinds, fetch census + the explicit extras."""
    out: Dict[str, Tuple[str, ...]] = {
        s: _FETCH_KINDS for s in fetch_sites_from_inventory(path)
    }
    out.update(EXTRA_SITES)
    return out


def make_schedule(seed: int, sites: Optional[Dict] = None) -> dict:
    """ONE deterministic scenario from ``seed``: armed failpoint specs
    plus the pipeline shape to run them under.  Pure function of the
    seed and the (sorted) site inventory — tests pin same-seed
    equality."""
    if sites is None:
        sites = enrolled_sites()
    rng = random.Random(seed)
    n = rng.randint(1, 3)
    armed: Dict[str, str] = {}
    for site in rng.sample(sorted(sites), n):
        kind = rng.choice(sites[site])
        if kind == "delay":
            spec = f"delay@{rng.randint(1, 25)}"
        elif kind == "truncate":
            spec = f"truncate@{rng.randint(5, 60)}"
        elif kind == "oom" and rng.random() < 0.5:
            spec = f"oom*{rng.randint(1, 2)}"
        else:
            spec = kind
        armed[site] = spec
    has_abort = any(s.startswith("abort") for s in armed.values())
    checkpoint = has_abort or rng.random() < 0.4
    engine = rng.choice(("auto", "level", "fused"))
    return {
        "seed": seed,
        "failpoints": armed,
        "engine": engine,
        "checkpoint": checkpoint,
        "cadence": rng.choice((1, 2, 3)) if checkpoint else 1,
    }


def _base_seed() -> int:
    """``FA_CHAOS_SEED`` offset for the whole seed set — strict parse
    (the FA_NO_PALLAS contract: a typo'd seed silently soaking seed 0
    would report coverage that never ran)."""
    from fastapriori_tpu.utils.env import env_int

    return env_int("FA_CHAOS_SEED", 0, minimum=0)


def make_inputs(root: str) -> str:
    """Deterministic tiny corpus (the failpoint_smoke shape, plus a
    planted deep itemset so multi-segment fused-checkpoint schedules
    exercise more than one segment)."""
    rng = random.Random(11)
    items = [str(i) for i in range(1, 13)]
    weights = [1.0 / (i + 1) for i in range(12)]
    lines = [
        " ".join(rng.choices(items, weights=weights, k=rng.randint(1, 6)))
        for _ in range(130)
    ] + ["1 2 3 4 5"] * 20
    inp = os.path.join(root, "in") + os.sep
    os.makedirs(inp)
    # lint: waive G009 -- soak INPUT fixtures in a fresh temp dir, not run artifacts
    with open(os.path.join(inp, "D.dat"), "w") as f:
        f.writelines(l + "\n" for l in lines)
    # lint: waive G009 -- soak INPUT fixtures in a fresh temp dir, not run artifacts
    with open(os.path.join(inp, "U.dat"), "w") as f:
        f.writelines(l + "\n" for l in lines[:25])
    return inp


class Outcome:
    __slots__ = ("kind", "detail")

    def __init__(self, kind: str, detail: str = ""):
        self.kind = kind  # identical | classified | killed_resumed | FAIL
        self.detail = detail


def _run_cli_bounded(argv: List[str], timeout_s: float):
    """cli.main on a worker thread with a wall bound — the harness-side
    no-hang assertion (the in-process analog of the dispatch watchdog).
    Returns ``(rc_or_None, exception_or_None, hung)``."""
    from fastapriori_tpu.cli import main

    box: list = []

    def run() -> None:
        try:
            box.append(("rc", main(argv)))
        # lint: waive G006 -- captured (InjectedAbort is a BaseException) and judged against the invariant by the caller
        except BaseException as exc:
            box.append(("err", exc))

    worker = threading.Thread(target=run, daemon=True)
    worker.start()
    worker.join(timeout_s)
    if not box:
        return None, None, True
    kind, payload = box[0]
    if kind == "rc":
        return payload, None, False
    return None, payload, False


def _classified(exc: BaseException, armed: Dict[str, str]) -> bool:
    """The invariant's "classified error naming the site" test: the
    failure must be one of the contract's error shapes AND traceable —
    its message names an armed site, carries an injected-failpoint
    marker, cites the artifact-validation contract (manifest rejection
    of a torn artifact names the FILE, not the write site), or
    classifies transient under retry.classify.  A stray InputError
    with none of those markers is a real regression and must count as
    an UNCLASSIFIED crash, not ride the invariant."""
    from fastapriori_tpu.errors import InputError
    from fastapriori_tpu.reliability import failpoints, retry

    if isinstance(exc, failpoints.InjectedAbort):
        return True
    msg = str(exc)
    named = any(site in msg for site in armed) or (
        "injected failpoint" in msg
    )
    contract = any(
        w in msg for w in ("truncated", "corrupt", "manifest", "checkpoint")
    )
    if isinstance(exc, (InputError, FileNotFoundError, OSError)):
        return named or contract or retry.classify(exc) == "transient"
    if isinstance(exc, RuntimeError):
        return retry.classify(exc) == "transient" or named
    return False


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def _validate_artifacts(out: str) -> Optional[str]:
    """Manifest cross-validation of every committed artifact under
    ``out``: returns the name of the first artifact the manifest
    REJECTS (the truncation-detected case), None when all validate."""
    from fastapriori_tpu.errors import InputError
    from fastapriori_tpu.io import resume as resume_io

    try:
        manifest = resume_io.load_manifest(out)
    except (InputError, FileNotFoundError):
        return "MANIFEST.json"
    for name in manifest:
        try:
            resume_io.validate_artifact_bytes(
                out, name, _read(out + name), manifest
            )
        except (InputError, FileNotFoundError):
            return name
    return None


def run_scenario(
    schedule: dict, inp: str, root: str, clean: Dict[str, bytes],
    timeout_s: float,
) -> Outcome:
    """One scenario under the invariant (module docstring)."""
    from fastapriori_tpu.io.checkpoint import (
        checkpoint_available,
        validate_checkpoint,
    )
    from fastapriori_tpu.reliability import failpoints, ledger

    from fastapriori_tpu.obs import flight

    out = os.path.join(root, f"s{schedule['seed']}") + os.sep
    os.makedirs(out)

    def fail(detail: str) -> Outcome:
        """Every FAIL ships its own post-mortem (ISSUE 11): the flight
        recorder's ring — the ledger/span/watchdog events leading up to
        the failure — dumps manifest-committed next to the scenario's
        artifacts (the harness keeps the workdir on failure)."""
        try:
            path = flight.dump(out, f"chaos: {detail}"[:400])
            print(f"chaos: flight recorder dumped: {path}")
        # lint: waive G006 G009 -- best-effort post-mortem on an already-failed scenario
        except Exception:
            pass
        return Outcome("FAIL", detail)
    argv = [
        inp, out, "--min-support", "0.08",
        "--engine", schedule["engine"],
    ]
    if schedule["checkpoint"]:
        argv += [
            "--checkpoint-every-level",
            "--checkpoint-cadence", str(schedule["cadence"]),
        ]
    ledger.reset()
    failpoints.disarm_all()
    for site, spec in schedule["failpoints"].items():
        failpoints.arm(site, spec)
    try:
        rc, exc, hung = _run_cli_bounded(argv, timeout_s)
    finally:
        failpoints.disarm_all()
    armed = schedule["failpoints"]
    degraded = ledger.summary()
    if hung:
        return fail(
            f"hang: no result within {timeout_s}s under {armed}"
        )
    truncated = any("truncate" in s for s in armed.values())
    if exc is not None:
        if not _classified(exc, armed):
            return fail(
                f"unclassified crash {type(exc).__name__}: {exc} "
                f"under {armed}"
            )
        if isinstance(exc, failpoints.InjectedAbort) and (
            schedule["checkpoint"] and checkpoint_available(out)
        ):
            # The kill contract: a structurally valid checkpoint that
            # resumes to byte-identical output.  A schedule may ALSO
            # have armed a truncation against the checkpoint write —
            # then validation REJECTING the torn file is exactly the
            # manifest contract (invariant case 2), while a rejected
            # checkpoint with no truncation armed is real corruption.
            from fastapriori_tpu.errors import InputError
            try:
                validate_checkpoint(out)
            except InputError as verr:
                if any(
                    site.startswith("write.checkpoint")
                    and "truncate" in spec
                    for site, spec in armed.items()
                ):
                    return Outcome(
                        "classified",
                        f"torn checkpoint rejected: {verr}",
                    )
                return fail(
                    f"corrupt checkpoint with no truncation armed: "
                    f"{verr} under {armed}"
                )
            rc2, exc2, hung2 = _run_cli_bounded(
                [inp, out, "--min-support", "0.08",
                 "--resume-from", out],
                timeout_s,
            )
            if hung2 or exc2 is not None or rc2 != 0:
                return fail(
                    f"resume after kill failed (rc={rc2}, exc={exc2}) "
                    f"under {armed}"
                )
            for name, want in clean.items():
                if _read(out + name) != want:
                    return fail(
                        f"resumed {name} differs from clean run "
                        f"under {armed}"
                    )
            return Outcome("killed_resumed", str(armed))
        return Outcome("classified", f"{type(exc).__name__} under {armed}")
    if rc == 2:
        return Outcome("classified", f"exit 2 under {armed}")
    if rc != 0:
        return fail(f"unexpected exit code {rc} under {armed}")
    for name, want in clean.items():
        if _read(out + name) == want:
            continue
        if truncated and _validate_artifacts(out) is not None:
            # Not silent: the manifest rejects the torn artifact, which
            # is the truncation contract (io/writer.py).
            return Outcome("classified", f"truncation detected ({name})")
        return fail(
            f"SILENT CORRUPTION: {name} differs (rc 0, "
            f"degraded={degraded}) under {armed}"
        )
    kind = "degraded" if degraded.get("cascade") else "identical"
    return Outcome(kind, f"degraded={degraded} under {armed}")


def main_chaos(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--seeds", default="0,1,2,3",
        help="comma-separated base seeds (offset by FA_CHAOS_SEED)",
    )
    ap.add_argument(
        "--scenarios", type=int, default=2,
        help="scenarios per seed (seed*100+i derives each schedule)",
    )
    ap.add_argument(
        "--budget-s", type=float, default=150.0,
        help="soft wall budget: no new scenario starts past it "
        "(dropped scenarios are LOGGED, never silently skipped)",
    )
    ap.add_argument(
        "--scenario-timeout-s", type=float, default=90.0,
        help="per-scenario hang bound (the no-hang invariant)",
    )
    ap.add_argument("--keep", action="store_true", help="keep workdirs")
    args = ap.parse_args(argv)

    # 8 virtual CPU devices BEFORE any backend init, so the sharded
    # paths (sparse exchange, vertical lanes, sharded rules) are real
    # multi-device programs in the soak — the conftest mesh, standalone
    # (XLA_FLAGS works on every pinned jax; jax_num_cpu_devices only on
    # newer ones).  Compile-log lines off: the soak's stdout is its
    # per-scenario verdict stream.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        )
    os.environ.setdefault("FA_NO_COMPILE_LOG", "1")
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except (AttributeError, RuntimeError):  # old jax / already init
        pass
    from fastapriori_tpu.cli import main as cli_main

    base = _base_seed()
    seeds = [int(s) + base for s in args.seeds.split(",") if s.strip()]
    t0 = time.monotonic()
    root = tempfile.mkdtemp(prefix="fa_chaos_")
    failures: List[str] = []
    tallies: Dict[str, int] = {}
    ran = dropped = 0
    try:
        inp = make_inputs(root)
        out_clean = os.path.join(root, "clean") + os.sep
        os.makedirs(out_clean)
        if cli_main([inp, out_clean, "--min-support", "0.08"]) != 0:
            print("chaos: FAIL: clean run failed", file=sys.stderr)
            return 1
        clean = {
            n: _read(out_clean + n)
            for n in ("freqItemset", "recommends")
        }
        sites = enrolled_sites()
        print(
            f"chaos: {len(sites)} enrolled sites "
            f"({len(fetch_sites_from_inventory())} from the fetch "
            f"census), seeds {seeds} x {args.scenarios}",
        )
        tainted = False
        for seed in seeds:
            for i in range(args.scenarios):
                if tainted or time.monotonic() - t0 > args.budget_s:
                    dropped += 1
                    continue
                schedule = make_schedule(seed * 100 + i, sites)
                outcome = run_scenario(
                    schedule, inp, root, clean, args.scenario_timeout_s
                )
                ran += 1
                tallies[outcome.kind] = tallies.get(outcome.kind, 0) + 1
                tag = "FAIL" if outcome.kind == "FAIL" else "ok"
                print(
                    f"chaos[{schedule['seed']}] {tag} "
                    f"{outcome.kind}: {outcome.detail}"
                )
                if outcome.kind == "FAIL":
                    failures.append(outcome.detail)
                    if outcome.detail.startswith("hang"):
                        # The hung scenario's daemonized CLI thread is
                        # still running and shares the process-global
                        # ledger/failpoint registries — later scenarios
                        # would no longer be deterministic functions of
                        # their seed.  The soak is already failed; stop
                        # scheduling rather than report tainted verdicts.
                        tainted = True
                        print(
                            "chaos: process state tainted by the hung "
                            "scenario — remaining scenarios skipped",
                            file=sys.stderr,
                        )
    finally:
        # A failed soak keeps its workdirs regardless of --keep: the
        # FAIL scenarios' flight-recorder dumps (<out>flight.json — the
        # post-mortem, ISSUE 11) live there, and deleting the evidence
        # of the failure the soak exists to catch would be absurd.
        if not args.keep and not failures:
            shutil.rmtree(root, ignore_errors=True)
        else:
            print(f"chaos: workdirs kept under {root}")
    wall = time.monotonic() - t0
    if dropped:
        print(
            f"chaos: {dropped} scenario(s) dropped (the "
            f"{args.budget_s}s budget, or taint after a hang) — "
            "coverage was NOT complete",
            file=sys.stderr,
        )
    print(
        f"chaos: {'FAIL' if failures else 'OK'} scenarios={ran} "
        f"{tallies} wall={wall:.1f}s (budget {args.budget_s}s)"
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main_chaos())
