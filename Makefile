# Developer entry points.  `make ci` is the tier-1 flow: lint (full
# surface + inventory drift check, wall-time budgeted), tests, then the
# failpoint smoke pass (reliability wiring under injected failure — see
# tools/failpoint_smoke.py).

.PHONY: lint test smoke serve-smoke obs-smoke chaos chaos-mp ci baseline inventory native

# Default paths cover the whole tree: fastapriori_tpu tests bench.py
# __graft_entry__.py tools (tools/lint/cli.py DEFAULT_PATHS).
lint:
	python -m tools.lint --baseline tools/lint/baseline.json --check-inventory

test:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
	    --continue-on-collection-errors -p no:cacheprovider

smoke:
	env JAX_PLATFORMS=cpu python tools/failpoint_smoke.py

# Serving-tier smoke (ISSUE 10): build + warm-restart byte-identical,
# seeded open-loop burst, overload spike -> recorded sheds + recovery,
# transient absorb on the serving fetch.
serve-smoke:
	env JAX_PLATFORMS=cpu python tools/serve_smoke.py

# Observability smoke (ISSUE 11): mine+serve under --trace
# (Perfetto-loadable artifact, span hierarchy, counter tracks),
# metrics-dump/mid-burst scrape, tracing-off overhead pin.
obs-smoke:
	env JAX_PLATFORMS=cpu python tools/obs_smoke.py

# Seeded chaos soak: deterministic failpoint schedules over the
# censused site inventory, full-pipeline invariant check (ISSUE 9;
# FA_CHAOS_SEED offsets the seed set).
chaos:
	env JAX_PLATFORMS=cpu python tools/chaos.py \
	    --seeds 0,4,6,9 --scenarios 3 --budget-s 120

# Multi-process fault-domain soak (ISSUE 12): real 2-subprocess meshes
# over the file-transport quorum — seeded kill-mid-level / divergence
# injection / coordinator-flap / heartbeat-delay schedules under the
# extended invariant (survivors byte-identical or classified naming
# the rank; never a hang or a mixed-epoch artifact).  The seed set
# pins all three elastic-mesh kinds (ISSUE 17: continuation after a
# kill mid-level, a kill at the W_s rendezvous, and retry-budget
# exhaustion) alongside kill/divergence/flap/wstotals.
chaos-mp:
	env JAX_PLATFORMS=cpu python tools/chaos.py --procs 2 \
	    --seeds 0,2,5 --scenarios 3 --budget-s 120

ci: lint test smoke serve-smoke obs-smoke chaos chaos-mp

# Ratchet reset — only alongside the change that justifies it.
baseline:
	python -m tools.lint \
	    --baseline tools/lint/baseline.json --write-baseline

# Regenerate tools/lint/inventory.json + env_registry.json + the README
# knob table; commit the churn in the PR that caused it.
inventory:
	python -m tools.lint --write-inventory

native:
	$(MAKE) -C fastapriori_tpu/native
