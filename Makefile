# Developer entry points.  `make ci` is the tier-1 flow: lint, tests,
# then the failpoint smoke pass (reliability wiring under injected
# failure — see tools/failpoint_smoke.py).

.PHONY: lint test smoke ci baseline native

lint:
	python -m tools.lint fastapriori_tpu tests --baseline tools/lint/baseline.json

test:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
	    --continue-on-collection-errors -p no:cacheprovider

smoke:
	env JAX_PLATFORMS=cpu python tools/failpoint_smoke.py

ci: lint test smoke

# Ratchet reset — only alongside the change that justifies it.
baseline:
	python -m tools.lint fastapriori_tpu tests \
	    --baseline tools/lint/baseline.json --write-baseline

native:
	$(MAKE) -C fastapriori_tpu/native
