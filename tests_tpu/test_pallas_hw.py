"""Mosaic-compiled (interpret=False) runs of the Pallas level kernel on
real TPU hardware — the proof that the kernel legalizes and is bit-exact
where it matters, not just in interpret mode (tests/test_pallas.py).

Run as ``python -m pytest tests_tpu/ -q`` when the accelerator tunnel is
up.  Skips itself at runtime when the backend is CPU or unavailable (no
conftest here on purpose: a second conftest.py would collide with
tests/conftest.py under plain ``pytest`` from the repo root, and eager
backend probing at collection time would break tests/'s
``jax_num_cpu_devices`` pinning).

Reference hot loop being replaced: FastApriori.scala:143-152 (prefix AND
+ weighted extension count).
"""

import numpy as np
import pytest


def _require_accelerator():
    """Runtime (not collection-time) skip so importing this module never
    initializes a JAX backend."""
    import jax

    try:
        backend = jax.default_backend()
    except Exception:  # backend init failed (tunnel down)
        backend = None
    # mirror parallel/mesh.py's gate: anything that is not CPU compiles
    # Mosaic for real (the axon tunnel registers as backend "tpu")
    if backend in (None, "cpu"):
        pytest.skip(f"no accelerator backend (got {backend!r})")


def _case(seed, t, m, f, k, max_w):
    rng = np.random.default_rng(seed)
    bitmap = (rng.random((t, f)) < 0.2).astype(np.int8)
    s = np.zeros((m, f), dtype=np.int8)
    for i in range(m // 2):
        cols = rng.choice(f, size=k - 1, replace=False)
        s[i, cols] = 1
    w = rng.integers(1, max_w + 1, size=t).astype(np.int64)
    wb = (bitmap * w[:, None]).astype(np.int8)
    return bitmap, w, wb, s


@pytest.mark.parametrize("k,max_w", [(3, 5), (3, 127), (5, 5)])
def test_pallas_level_counts_compiled_on_tpu(k, max_w):
    _require_accelerator()
    import jax.numpy as jnp

    from fastapriori_tpu.ops.pallas_level import (
        M_TILE,
        T_TILE,
        level_counts_pallas,
    )

    bitmap, w, wb, s = _case(0, T_TILE * 2, M_TILE, 256, k, max_w)
    got = np.asarray(
        level_counts_pallas(
            jnp.asarray(bitmap),
            jnp.asarray(wb),
            jnp.asarray(s),
            jnp.int32(k - 1),
            interpret=False,  # Mosaic compile, not interpret
        )
    )
    overlap = bitmap.astype(np.int64) @ s.astype(np.int64).T
    common = overlap == (k - 1)
    expected = (common * w[:, None]).T @ bitmap.astype(np.int64)
    assert (got == expected).all()


@pytest.mark.parametrize("engine", ["fused", "level"])
def test_engines_on_chip_match_oracle(engine):
    """Both mining engines end-to-end on the real accelerator vs the
    oracle (the CPU suite pins JAX to 8 virtual host devices; this is
    the same assertion on actual hardware)."""
    _require_accelerator()
    from fastapriori_tpu import oracle
    from fastapriori_tpu.config import MinerConfig
    from fastapriori_tpu.models.apriori import FastApriori

    rng = np.random.default_rng(23)
    lines = [
        [str(x) for x in rng.choice(50, size=rng.integers(2, 11), replace=False)]
        for _ in range(3000)
    ] + [["1", "2", "3"]] * 200  # heavy duplicate: >127 weight digit path
    expected, _, _ = oracle.mine(lines, 0.03)
    got, _, _ = FastApriori(
        config=MinerConfig(min_support=0.03, engine=engine)
    ).run(lines)
    assert dict(got) == dict(expected)
